#!/usr/bin/env bash
# Serving smoke gate: the web-service sample's --self-test end to end
# on CPU — registry deploy + warmup, concurrent clients, a hot-swap
# mid-traffic with zero failed requests, a coherent /metrics, a traced
# request whose phases account for its span wall, and a Prometheus
# scrape round-tripped through the stdlib exposition parser
# (observability.metrics.parse_prometheus_text — an unparseable line
# fails the self-test, and the grep below keeps the scrape from being
# silently skipped).
#
# Runnable standalone (like check_collection.sh) and cheap enough for
# CI: one process, ~1 min on a cold CPU.  The timeout wrapper keeps a
# wedged dispatcher/server from hanging the gate forever.
#
# Two forced host devices make the run MULTI-REPLICA end to end: the
# registry deploys with replicas="all", so the self-test exercises the
# compile-once/place-everywhere path, the cross-replica scheduler, and
# the per-replica metrics — on plain CPU.
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python apps/web-service-sample/web_service.py --self-test)
printf '%s\n' "$out"
grep -q "prometheus scrape OK" <<<"$out" || {
    echo "smoke FAIL: self-test never scraped /metrics?format=prometheus" >&2
    exit 1
}
grep -q "trace check: " <<<"$out" || {
    echo "smoke FAIL: self-test never verified a request trace" >&2
    exit 1
}
grep -q "replica check: 2 replicas" <<<"$out" || {
    echo "smoke FAIL: self-test never verified multi-replica serving" >&2
    exit 1
}
echo "serving smoke OK"
