#!/usr/bin/env bash
# Serving smoke gate: the web-service sample's --self-test end to end
# on CPU — registry deploy + warmup, concurrent clients, a hot-swap
# mid-traffic with zero failed requests, a coherent /metrics, a traced
# request whose phases account for its span wall, and a Prometheus
# scrape round-tripped through the stdlib exposition parser
# (observability.metrics.parse_prometheus_text — an unparseable line
# fails the self-test, and the grep below keeps the scrape from being
# silently skipped).
#
# Runnable standalone (like check_collection.sh) and cheap enough for
# CI: one process, ~1 min on a cold CPU.  The timeout wrapper keeps a
# wedged dispatcher/server from hanging the gate forever.
#
# Two forced host devices make the run MULTI-REPLICA end to end: the
# registry deploys with replicas="all", so the self-test exercises the
# compile-once/place-everywhere path, the cross-replica scheduler, and
# the per-replica metrics — on plain CPU.
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python apps/web-service-sample/web_service.py --self-test)
printf '%s\n' "$out"
grep -q "prometheus scrape OK" <<<"$out" || {
    echo "smoke FAIL: self-test never scraped /metrics?format=prometheus" >&2
    exit 1
}
grep -q "trace check: " <<<"$out" || {
    echo "smoke FAIL: self-test never verified a request trace" >&2
    exit 1
}
grep -q "replica check: 2 replicas" <<<"$out" || {
    echo "smoke FAIL: self-test never verified multi-replica serving" >&2
    exit 1
}

# Elastic serving gate: a short spike-profile loadtest under the same
# 2 forced host devices — the autoscaler must scale up INTO the spike
# and back down after it (zero cold compiles across both transitions,
# no flapping: the selfcheck enforces all three), and the Prometheus
# scrape carrying the new families (zoo_autoscale_events_total,
# zoo_shed_total{class}, zoo_model_replicas_active, ...) must
# round-trip the stdlib parser.
lt=$(timeout -k 10 360 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python bench.py loadtest --profile spike --quick --selfcheck)
printf '%s\n' "$lt"
grep -Eq "LOADTEST_AUTOSCALE up=[1-9][0-9]* down=[1-9]" <<<"$lt" || {
    echo "smoke FAIL: spike loadtest missing a scale-up + scale-down" >&2
    exit 1
}
grep -q "LOADTEST_SCRAPE_OK" <<<"$lt" || {
    echo "smoke FAIL: loadtest scrape of the elastic families failed" >&2
    exit 1
}
# zoolint v2 runtime half: the invariant-snapshot sanitizer must have
# run over a quiesced post-drain serve window and found every
# in-flight/slot/ticket gauge (and the thread count) back at rest —
# the runtime twin of the ZL701/702 exception-path leak rules
grep -q "LOADTEST_INVARIANTS_OK" <<<"$lt" || {
    echo "smoke FAIL: loadtest never ran (or failed) the zoolint" \
         "invariant-snapshot check on the quiesced serve window" >&2
    exit 1
}
grep -q "LOADTEST_SELFCHECK_OK" <<<"$lt" || {
    echo "smoke FAIL: loadtest selfcheck gates failed" >&2
    exit 1
}

# Continuous-batching gate: the slot-array decode engine's --quick
# selfcheck under the same 2 forced host devices — useful-token
# throughput >= 1.5x naive batch-of-requests scan decode on a
# heavy-tailed mixed-length workload, per-slot streams bit-exact vs
# the scan path, exactly one compile per (bucket, capacity) plan, and
# a sanitize-clean warmed decode loop.  Decode engine v2 adds three
# gated legs to the same run: per-slot sampling (overhead bound vs
# greedy + bit-identical fixed-seed replay), the prefix-KV pool
# (>= 1.5x useful tokens/s on a shared-prefix mix, vacuousness-checked
# both directions), and speculative decoding (beats the plain engine
# on a greedy heavy-tailed mix, acceptance rate reported).
dc=$(timeout -k 10 900 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python bench.py decode --quick --selfcheck)
printf '%s\n' "$dc"
grep -Eq "DECODE_TOKENS_GATE ratio=[0-9.]+x .* PASS" <<<"$dc" || {
    echo "smoke FAIL: decode tokens/s gate missing or failed" >&2
    exit 1
}
grep -Eq "DECODE_SAMPLING_GATE ratio=[0-9.]+x .*replay=ok .*PASS" <<<"$dc" || {
    echo "smoke FAIL: sampled-decode overhead/replay gate missing or" \
         "failed" >&2
    exit 1
}
grep -Eq "DECODE_PREFIX_GATE ratio=[0-9.]+x .*PASS" <<<"$dc" || {
    echo "smoke FAIL: prefix-KV pool gate missing or failed" >&2
    exit 1
}
grep -Eq "DECODE_SPEC_GATE ratio=[0-9.]+x .*acceptance=[0-9.]+ .*PASS" <<<"$dc" || {
    echo "smoke FAIL: speculative decode gate missing or failed" >&2
    exit 1
}
grep -q "DECODE_SELFCHECK_OK" <<<"$dc" || {
    echo "smoke FAIL: decode selfcheck gates failed" >&2
    exit 1
}

# Persistent-executable-store gate: the two-process cold-start leg.
# bench.py coldstart spawns a FIRST process that deploys (and
# decode-warms) against an empty store and exits, then a SECOND fresh
# process that repeats the identical deploy against the warmed store —
# which must record exactly 0 backend_compile events inside deploy()
# and DecodeEngine.warmup(), with outputs bit-identical to the first
# process's.
cs=$(timeout -k 10 590 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python bench.py coldstart --quick --selfcheck)
printf '%s\n' "$cs"
grep -Eq "COLDSTART_ZERO_COMPILE deploy=0 decode_warmup=0 .*PASS" <<<"$cs" || {
    echo "smoke FAIL: warm-store second process was not zero-compile" >&2
    exit 1
}
grep -q "COLDSTART_SELFCHECK_OK" <<<"$cs" || {
    echo "smoke FAIL: coldstart selfcheck gates failed" >&2
    exit 1
}

# Serving-density gate: the weight/executable pager under 3x
# overcommit — 6 models over a 2-model resident budget, mixed traffic
# across all of them.  Every response must be bit-identical to an
# unpaged reference registry (DENSITY_BITEXACT wrong=0), every cold
# fault must be an execstore rehydrate (0 backend_compile events in
# the whole traffic window, p99 penalty bounded), and a resident
# model's warmed hot path must provably never touch the pager (zero
# pager-lock acquisitions + zero compiles, sanitize-clean).
dn=$(timeout -k 10 590 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    python bench.py density --quick --selfcheck)
printf '%s\n' "$dn"
grep -Eq "DENSITY_BITEXACT wrong=0 .*PASS" <<<"$dn" || {
    echo "smoke FAIL: paged serving returned wrong/failed results" >&2
    exit 1
}
grep -Eq "DENSITY_COLD_FAULT .*compiles=0 .*PASS" <<<"$dn" || {
    echo "smoke FAIL: cold faults compiled (store did not serve them)" \
         "or the p99 fault penalty is unbounded" >&2
    exit 1
}
grep -Eq "DENSITY_RESIDENT_HOTPATH_OK lock_acq=0 compiles=0 .*PASS" <<<"$dn" || {
    echo "smoke FAIL: a resident model's hot path touched the pager" >&2
    exit 1
}
grep -q "DENSITY_SELFCHECK_OK" <<<"$dn" || {
    echo "smoke FAIL: density selfcheck gates failed" >&2
    exit 1
}

# Fleet-serving gate: a 2-worker fleet (real supervised processes,
# shared execstore) behind the router, under open-loop traffic,
# through a rolling upgrade AND a SIGKILL'd worker — zero failed
# requests in both legs, only the FIRST activation of each version
# compiles (every later worker and the restarted one warm from the
# store with 0), outputs bit-identical to a single-process registry,
# and the rank-merged fleet scrape parser-clean.  Fleet v2 adds four
# gated legs to the same run: the negotiated binary wire (bit-exact
# A/B vs JSON with a measured bytes/request reduction), the
# router-path throughput floor, the elastic pool (warm zero-compile
# scale-up, then an autoscaler-driven scale-down mid-traffic that
# drains the victim with zero failed requests), and residency-aware
# routing over a 3x-overcommitted pager fleet (affinity hit-rate +
# bounded cold-fault p99, bit-exact).  The distributed-tracing legs
# stitch the kill's retried request across its worker legs, rebuild
# a trace from the postmortem file alone, attribute >= 95% of the
# tail exemplars' wall time, and bound tracing overhead.
fl=$(timeout -k 10 590 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python bench.py fleet --quick --selfcheck)
printf '%s\n' "$fl"
grep -Eq "FLEET_ROLLING_UPGRADE_OK .*failed=0" <<<"$fl" || {
    echo "smoke FAIL: fleet rolling upgrade dropped requests or never ran" >&2
    exit 1
}
grep -Eq "FLEET_WORKER_KILL_OK .*failed=0 .*replay_compiles=0" <<<"$fl" || {
    echo "smoke FAIL: fleet worker-kill leg dropped requests or the" \
         "restarted worker did not warm zero-compile from the store" >&2
    exit 1
}
grep -Eq "FLEET_WIRE_BINARY_OK .*reduction=" <<<"$fl" || {
    echo "smoke FAIL: fleet binary-wire A/B missing, not bit-exact," \
         "or no measured byte reduction" >&2
    exit 1
}
grep -Eq "FLEET_AFFINITY_OK .*failed=0" <<<"$fl" || {
    echo "smoke FAIL: residency-affinity leg missing, hit-rate/p99" \
         "out of bounds, or requests failed" >&2
    exit 1
}
grep -Eq "FLEET_SCALE_DOWN_OK failed=0" <<<"$fl" || {
    echo "smoke FAIL: elastic scale-down dropped requests or the" \
         "autoscaler never drove the pool" >&2
    exit 1
}
grep -Eq "FLEET_TRACE_STITCH_OK .*postmortem_stitch=y" <<<"$fl" || {
    echo "smoke FAIL: distributed-trace stitch leg missing, exemplar" \
         "attribution under 95%, or the postmortem path broke" >&2
    exit 1
}
grep -q "FLEET_SELFCHECK_OK" <<<"$fl" || {
    echo "smoke FAIL: fleet selfcheck gates failed" >&2
    exit 1
}

# Sharded-serving gate: replica GROUPS over sub-meshes (2 groups of 2
# on 4 forced host devices).  Every group must serve bit-identically
# to the single-device jit (the column rule gathers, never psums),
# the second group must be a deserialize — zero extra compiles — and
# a warm-store re-deploy must compile nothing; the pager must refuse
# a partially placed group (group-atomic residency), and the sharded
# decode engine must stream bit-identically to the unsharded one.
sh=$(timeout -k 10 590 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    python bench.py sharded --quick --selfcheck)
printf '%s\n' "$sh"
grep -Eq "SHARDED_BITEXACT_OK .*PASS" <<<"$sh" || {
    echo "smoke FAIL: a replica group diverged from the" \
         "single-device jit" >&2
    exit 1
}
grep -Eq "SHARDED_ZERO_COMPILE group2=0 warm_redeploy=0 PASS" <<<"$sh" || {
    echo "smoke FAIL: group 2 or the warm re-deploy compiled" \
         "(placement must be a deserialize)" >&2
    exit 1
}
grep -Eq "SHARDED_PAGER_ATOMIC wrong=0 .*refused=True .*PASS" <<<"$sh" || {
    echo "smoke FAIL: sharded paging went wrong or a partial group" \
         "placement was installed" >&2
    exit 1
}
grep -q "SHARDED_SELFCHECK_OK" <<<"$sh" || {
    echo "smoke FAIL: sharded selfcheck gates failed" >&2
    exit 1
}
echo "serving smoke OK"
