#!/usr/bin/env bash
# Serving smoke gate: the web-service sample's --self-test end to end
# on CPU — registry deploy + warmup, concurrent clients, a hot-swap
# mid-traffic with zero failed requests, and a coherent /metrics.
#
# Runnable standalone (like check_collection.sh) and cheap enough for
# CI: one process, ~1 min on a cold CPU.  The timeout wrapper keeps a
# wedged dispatcher/server from hanging the gate forever.
set -euo pipefail
cd "$(dirname "$0")/.."
timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    python apps/web-service-sample/web_service.py --self-test
echo "serving smoke OK"
