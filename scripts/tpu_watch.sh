#!/bin/bash
# Probe the TPU tunnel every PERIOD seconds; the moment it answers, run the
# full bench plan and save the JSON line.  Exits 0 with a saved artifact on
# success, exits 3 when DEADLINE seconds pass with no live chip.
#
# The probe is the same time-boxed child as bench.py::_probe_tpu — a hung
# backend init must never block this loop inline.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO" || exit 2
PERIOD=${PERIOD:-600}
DEADLINE=${DEADLINE:-39600}   # 11h default
OUT=${OUT:-$REPO/BENCH_TPU_LIVE.json}
START=$(date +%s)
N=0
while true; do
  N=$((N + 1))
  NOW=$(date +%s)
  if [ $((NOW - START)) -gt "$DEADLINE" ]; then
    echo "[tpu_watch] deadline reached after $N probes — chip never answered"
    exit 3
  fi
  if timeout 300 python - <<'EOF'
import jax, jax.numpy as jnp
a = jnp.ones((256, 256), jnp.bfloat16)
jax.jit(lambda a: a @ a)(a).block_until_ready()
assert jax.devices()[0].platform != "cpu"
print("TPU_PROBE_OK")
EOF
  then
    echo "[tpu_watch] probe $N: ALIVE at $(date -u +%H:%M:%S) — running bench"
    if timeout 4200 python bench.py > "$OUT" 2> "$REPO/tpu_watch_bench.log"; then
      echo "[tpu_watch] bench done -> $OUT"
      cat "$OUT"
      exit 0
    else
      echo "[tpu_watch] bench attempt failed (rc=$?) — see tpu_watch_bench.log; continuing to probe"
    fi
  else
    echo "[tpu_watch] probe $N: dead ($(date -u +%H:%M:%S))"
  fi
  sleep "$PERIOD"
done
