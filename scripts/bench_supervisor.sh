#!/bin/bash
# Round-5 window catcher: let an in-flight bench.py finish its TPU
# attempt, but skip its CPU fallback (a CPU artifact already exists from
# r4; host CPU time is better spent probing for the next live window),
# then keep tpu_watch.sh armed until the deadline.
PARENT=${1:?usage: bench_supervisor.sh <bench_parent_pid>}
LOG=${2:-/root/repo/bench_r5.log}
while kill -0 "$PARENT" 2>/dev/null; do
  if grep -q "platform=cpu" "$LOG" 2>/dev/null; then
    echo "[supervisor] bench moved to CPU fallback — stopping it"
    pkill -P "$PARENT" 2>/dev/null
    kill "$PARENT" 2>/dev/null
    break
  fi
  sleep 20
done
echo "[supervisor] arming tpu_watch"
PERIOD=${PERIOD:-300} exec /root/repo/scripts/tpu_watch.sh
