#!/usr/bin/env bash
# Training smoke gate (sibling of smoke_serving.sh): the fault-tolerant
# distributed-training drill end to end on CPU — a supervised 2-process
# jax.distributed pod trains a seeded workload, worker 1 SIGKILLs
# itself mid-epoch while a committed checkpoint's shard is byte-flipped
# post-commit, and the supervisor must reap the pod, relaunch it with
# ZOO_RESUME, convict + delete the corrupt tag, resume from the newest
# complete one, and finish with final params BIT-IDENTICAL to an
# uninterrupted run (bench.py faulttrain --quick --selfcheck; the full
# bench run adds the hang/watchdog leg).
#
# Runnable standalone like the other gates; the timeout wrapper keeps a
# wedged pod from hanging CI forever.
set -euo pipefail
cd "$(dirname "$0")/.."
ft=$(timeout -k 10 900 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    python bench.py faulttrain --quick --selfcheck)
printf '%s\n' "$ft"
grep -q "FAULT_DRILL_RESUME_OK" <<<"$ft" || {
    echo "smoke FAIL: crash+resume run did not reproduce the" \
         "uninterrupted run's params (or the drill never completed)" >&2
    exit 1
}
grep -q "corrupt_discarded=True" <<<"$ft" || {
    echo "smoke FAIL: the post-commit corrupted checkpoint was not" \
         "convicted and discarded at restore" >&2
    exit 1
}
grep -q "POSTMORTEM_OK" <<<"$ft" || {
    echo "smoke FAIL: the crash leg did not produce a pod_postmortem" \
         "naming the failed rank / last step / heartbeat age" >&2
    exit 1
}
grep -q "FAULTTRAIN_SELFCHECK_OK" <<<"$ft" || {
    echo "smoke FAIL: faulttrain selfcheck gates failed" >&2
    exit 1
}

# Sharded-training gates: the pjit train-state layout on 2 forced host
# devices — fsdp/fsdp_tp numerics vs replicated, gradient accumulation,
# exactly one compile in the traffic window, and the ZeRO opt-state
# memory win (bench.py trainshard --quick --selfcheck).
ts=$(timeout -k 10 900 env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python bench.py trainshard --quick --selfcheck)
printf '%s\n' "$ts"
grep -q "TRAINSHARD_BITEXACT" <<<"$ts" || {
    echo "smoke FAIL: trainshard never reached the sharded-vs-" \
         "replicated numerics gate" >&2
    exit 1
}
grep -q "TRAINSHARD_COMPILES=1" <<<"$ts" || {
    echo "smoke FAIL: the sharded train step did not compile exactly" \
         "once in the traffic window" >&2
    exit 1
}
grep -q "TRAINSHARD_SELFCHECK_OK" <<<"$ts" || {
    echo "smoke FAIL: trainshard selfcheck gates failed" >&2
    exit 1
}
echo "training smoke OK"
