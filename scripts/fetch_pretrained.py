#!/usr/bin/env python
"""Fetch public pretrained checkpoints for the model-zoo import path.

The zoo's pretrained story (reference:
ImageClassificationConfig.scala:34-50 serves pretrained models per
registry name) imports public checkpoints through
``models/weight_loading.py``.  This script downloads them where egress
exists; ``tests/test_pretrained_e2e.py`` picks them up from the cache
dir and runs the accuracy gate.

Usage:
    python scripts/fetch_pretrained.py [--dest ~/.cache/zoo_tpu_pretrained]
                                       [--model inception-v3|resnet-50|all]

Sources (both public, stable URLs):
  - inception-v3: tf.keras applications ImageNet weights
    (storage.googleapis.com/tensorflow/keras-applications/...)
  - resnet-50: torchvision IMAGENET1K_V1
    (download.pytorch.org/models/resnet50-0676ba61.pth)

Labeled validation images are NOT fetched (ImageNet samples are not
freely redistributable); the e2e test checks top-1 agreement between
the imported model and its source framework instead.
"""

import argparse
import os
import sys

DEST_DEFAULT = os.path.expanduser("~/.cache/zoo_tpu_pretrained")

KERAS_INCEPTION_V3 = (
    "https://storage.googleapis.com/tensorflow/keras-applications/"
    "inception_v3/inception_v3_weights_tf_dim_ordering_tf_kernels.h5")
TORCH_RESNET50 = "https://download.pytorch.org/models/resnet50-0676ba61.pth"


def fetch(url, dest):
    import urllib.request
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    if os.path.exists(dest):
        print(f"cached: {dest}")
        return dest
    print(f"fetching {url} -> {dest}")
    tmp = dest + ".part"
    urllib.request.urlretrieve(url, tmp)
    os.replace(tmp, dest)
    return dest


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dest", default=DEST_DEFAULT)
    ap.add_argument("--model", default="all",
                    choices=["inception-v3", "resnet-50", "all"])
    args = ap.parse_args()

    got = []
    try:
        if args.model in ("inception-v3", "all"):
            got.append(fetch(KERAS_INCEPTION_V3,
                             os.path.join(args.dest, "inception_v3.h5")))
        if args.model in ("resnet-50", "all"):
            got.append(fetch(TORCH_RESNET50,
                             os.path.join(args.dest, "resnet50_imagenet.pth")))
    except Exception as e:
        print(f"download failed ({type(e).__name__}: {e}) — no egress? "
              "Run this where the internet is reachable and copy "
              f"{args.dest} across.", file=sys.stderr)
        return 1
    print("done:", *got, sep="\n  ")
    print("verify end-to-end with: "
          "pytest tests/test_pretrained_e2e.py -q")
    return 0


if __name__ == "__main__":
    sys.exit(main())
