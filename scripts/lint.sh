#!/usr/bin/env bash
# zoolint gate: the JAX-aware static analyzer over the shipped package,
# against the checked-in baseline of justified suppressions.
#
# Exit 0  = clean modulo zoolint_baseline.json
# Exit 2  = usage — bad arguments or a broken baseline file (bad JSON /
#           empty justification)
# Exit 3  = findings (fix them, or baseline WITH a justification — see
#           docs/dev/zoolint.md for the workflow)
#
# The analyzer runs in --format json and this script renders each
# finding plus the per-code summary line CI logs key off.
#
# Pure AST — runs in seconds; importing the package pulls jax, so pin
# the platform to cpu like every other CI gate.
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(env JAX_PLATFORMS=cpu python -m analytics_zoo_tpu.tools.zoolint \
    analytics_zoo_tpu --baseline zoolint_baseline.json \
    --format json "$@") && rc=0 || rc=$?
if [ "$rc" -ne 0 ] && [ "$rc" -ne 3 ]; then
    # usage / broken baseline: the error already went to stderr and
    # stdout is not a JSON payload — don't try to summarize it
    [ -n "$out" ] && printf '%s\n' "$out"
    exit "$rc"
fi
case "$out" in
    "{"*) ;;
    *)
        # non-JSON success output: forwarded modes like
        # --update-baseline or --explain print plain text — pass it
        # through untouched instead of feeding it to the summarizer
        printf '%s\n' "$out"
        exit "$rc"
        ;;
esac
ZOOLINT_JSON="$out" python - <<'PY'
import json
import os

data = json.loads(os.environ["ZOOLINT_JSON"])
for f in data["findings"]:
    print("{path}:{line}:{col}: {code} [{symbol}] {message}"
          .format(**f))
s = data["summary"]
by = " ".join(f"{c}={n}" for c, n in sorted(s["by_code"].items())) \
    or "none"
print(f"zoolint summary: total={s['total']} "
      f"suppressed={s['suppressed']} stale={s['stale']} by_code: {by}")
PY
if [ "$rc" -ne 0 ]; then
    exit "$rc"
fi
# committed-contract gate: the live ContractIndex (wire ops, error
# codes, env vars, metric families) must match contracts_snapshot.json
# — a protocol change that never touched the snapshot never got its
# diff reviewed.  Drift: `zoolint contracts --update` + commit.
if env JAX_PLATFORMS=cpu python -m analytics_zoo_tpu.tools.zoolint \
    contracts --check > /dev/null; then
    echo "zoolint summary: contracts=ok"
else
    crc=$?
    echo "zoolint summary: contracts=drift"
    env JAX_PLATFORMS=cpu python -m analytics_zoo_tpu.tools.zoolint \
        contracts --check || true
    exit "$crc"
fi
echo "zoolint OK"
