#!/usr/bin/env bash
# zoolint gate: the JAX-aware static analyzer over the shipped package,
# against the checked-in baseline of justified suppressions.
#
# Exit 0  = clean modulo zoolint_baseline.json
# Exit 2  = NEW finding (fix it, or baseline it WITH a justification —
#           see docs/dev/zoolint.md for the workflow)
# Exit 3  = the baseline file itself is broken (bad JSON / empty
#           justification)
#
# Pure AST — runs in seconds; importing the package pulls jax, so pin
# the platform to cpu like every other CI gate.
set -euo pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m analytics_zoo_tpu.tools.zoolint \
    analytics_zoo_tpu --baseline zoolint_baseline.json "$@"
echo "zoolint OK"
