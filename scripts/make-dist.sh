#!/usr/bin/env bash
# Build the distributable wheel into dist/ (reference analog:
# make-dist.sh producing the dist/ consumed by *-with-zoo.sh).
# Offline-friendly: uses the already-installed setuptools, no build
# isolation, no network.
set -euo pipefail
cd "$(dirname "$0")/.."
# clear stale build state too — a non-isolated setuptools build reuses
# build/lib, which would ship since-deleted modules in the wheel
rm -rf dist build ./*.egg-info
pip wheel --no-deps --no-build-isolation -w dist .
echo "dist/ contents:"
ls -l dist/
echo
echo "install with:  pip install dist/analytics_zoo_tpu-*.whl"
echo "then run:      zoo-tpu-submit --help"
