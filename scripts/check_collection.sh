#!/usr/bin/env bash
# Fast collection gate: `pytest tests/ -q --co` must exit 0.
#
# A single bad import once zeroed out the whole suite silently (the
# `from jax import shard_map` drift killed 40+ test modules at
# COLLECTION on jax 0.4.37, so "0 failed" meant "0 collected").  Run
# this before the suite — it takes seconds and fails loudly on the
# first broken import.
set -euo pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m pytest tests/ -q --co \
    -p no:cacheprovider "$@" > /dev/null
echo "collection OK"
# zoolint rides the same fast gate: new static findings fail CI here,
# seconds after a push, not minutes into the suite (we already cd'd to
# the repo root above, so resolve lint.sh from there)
scripts/lint.sh
