"""Reference artifact builders (see :mod:`.artifact`).

A builder is ``fn(args: dict, params: dict | None) -> deploy kwargs``
— it turns the on-disk artifact back into the thing
``ModelRegistry.deploy`` accepts.  Two references ship here:

* :func:`mlp` — a seedable tanh-MLP jax forward over the artifact's
  weight dict (the fleet drill's workload: cheap, deterministic,
  bucket-ladder friendly);
* :func:`stub` — a pure-python duck-typed serving handle (numpy
  arithmetic on the rows, no jax work) used by the fake worker mode
  so the tier-1 supervisor/router tests exercise the whole
  fan-out/retry machinery without a backend or a compile.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional


def mlp(args: Dict[str, Any], params: Optional[Dict[str, Any]]
        ) -> Dict[str, Any]:
    """Layered tanh MLP whose depth comes from the weight dict itself
    (keys ``w0..w{n-1}``) — the same shape as the loadtest rig's
    workload, so fingerprints depend only on (weights, layer count,
    bucket config)."""
    import jax.numpy as jnp
    if params is None:
        raise ValueError("mlp builder needs artifact weights")
    n_layers = int(args.get("n_layers", len(params)))

    def forward(p, x):
        h = x
        for i in range(n_layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return h

    return {"jax_fn": forward, "params": params}


def lm(args: Dict[str, Any], params: Optional[Dict[str, Any]]
       ) -> Dict[str, Any]:
    """A deterministic TransformerLM behind the continuous-batching
    generate path: ``ensure_inference_ready`` initializes seeded, so
    every worker builds the SAME weights from the spec alone (no
    artifact weights needed) and the decode-plan execstore
    fingerprints line up fleet-wide — the web sample's /generate
    deployment, as a fleet artifact."""
    from ...models import TransformerLM
    net = TransformerLM(
        vocab_size=int(args.get("vocab_size", 32)),
        seq_len=int(args.get("seq_len", 64)),
        n_layers=int(args.get("n_layers", 1)),
        d_model=int(args.get("d_model", 16)),
        n_heads=int(args.get("n_heads", 2)))
    net.ensure_inference_ready()
    out = {"net": net,
           "decode_capacity": int(args.get("capacity", 2)),
           "decode_prompt_buckets": tuple(
               args.get("prompt_buckets", (8,))),
           "replicas": 1}
    # decode engine v2 knobs ride the artifact spec (json scalars), so
    # a fleet-wide deploy configures every worker's engine identically
    if args.get("prefix_pool"):
        out["decode_prefix_pool"] = int(args["prefix_pool"])
    return out


class StubModel:
    """A jax-free serving handle for the fake worker mode: implements
    the duck-typed registry surface (predict/warmup/close/
    serving_stats).  ``scale`` makes versions distinguishable
    bit-for-bit; ``delay_s`` shapes latency; ``die_after`` hard-kills
    the PROCESS on the nth predict — the deterministic
    worker-death-mid-request fixture the router retry tests use;
    ``expand`` widens each output row N× (trailing axis, so the
    row count the coalescer splits on is untouched), inflating the
    REPLY without inflating the request — the oversize-reply degrade
    fixture."""

    def __init__(self, scale: float = 1.0, delay_s: float = 0.0,
                 die_after: Optional[int] = None,
                 die_rank: Optional[int] = None,
                 expand: int = 1):
        self.scale = float(scale)
        self.delay_s = float(delay_s)
        self.expand = int(expand)
        # the death hook follows the train/faults.py one-shot
        # discipline: it only arms on a worker's FIRST incarnation
        # (a restarted worker must not re-die forever) and, with
        # die_rank set, only in that rank's process.  Identity comes
        # from the flightrec helpers — one parse of the supervision
        # env contract, shared with the recorder/log stamping.
        from ...observability import flightrec
        rank = flightrec._env_rank()
        inc = flightrec._env_incarnation()
        armed = (die_after is not None and inc == 0
                 and (die_rank is None or rank == int(die_rank)))
        self.die_after = die_after if armed else None
        self._lock = threading.Lock()
        self._served = 0
        self._closed = False

    def predict(self, inputs):
        import numpy as np
        with self._lock:
            self._served += 1
            served = self._served
        if self.die_after is not None and served >= self.die_after:
            # a real mid-request death: the reply never leaves
            os._exit(17)
        if self.delay_s:
            time.sleep(self.delay_s)
        out = np.asarray(inputs, dtype=np.float64) * self.scale
        if self.expand > 1:
            out = np.repeat(out, self.expand, axis=-1)
        return out

    def warmup(self, shapes, dtypes=None) -> float:
        return 0.0

    def close(self):
        self._closed = True

    def serving_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"stub": True, "served": self._served,
                    "scale": self.scale}


def stub(args: Dict[str, Any], params: Optional[Dict[str, Any]]
         ) -> Dict[str, Any]:
    return {"model": StubModel(
        scale=args.get("scale", 1.0),
        delay_s=args.get("delay_s", 0.0),
        die_after=args.get("die_after"),
        die_rank=args.get("die_rank"),
        expand=args.get("expand", 1))}
