"""Fleet router: the thin control plane in front of the worker plane.

Speaks the same admission/priority serving envelope OUTWARD that every
worker speaks inward (``predict_ex``/``generate_ex`` with deadline,
trace id, priority class, structured ``Overloaded``/
``DeadlineExceeded`` errors reconstructed concretely), and owns three
fleet-only jobs:

* **Scheduling** — least-outstanding-work across live workers, ties
  rotated (the ReplicaSet scheduler generalized across processes: one
  outstanding-count per worker instead of one in-flight slot per
  device).  A connection-level failure mid-request — the worker died
  under it — is retried ONCE on a sibling, exactly like replica fault
  tolerance retries a crashed device dispatch in-process; structured
  serving errors are real rejections and are NEVER retried.
* **Deploy fan-out** — ``deploy()`` persists the artifact (weights +
  spec) on the share ONCE, then activates the version on each worker
  ONE AT A TIME; every activation is the worker's own
  warm-before-swap, so the rolling upgrade never takes a worker out
  of service.  The first activation pays the compiles and populates
  the shared execstore; every later worker (and every restarted one)
  warms from the store in milliseconds with zero compiles — the
  instant-fleet-deploy promise, finally gated cross-process.
* **Observability** — ``metrics_text()`` scrapes every live worker
  and merges the expositions through the pod aggregator (workers are
  ranks: every sample gains a ``rank`` label, counters sum to a
  rank-less fleet total), plus the router's own families
  (``zoo_fleet_workers{state}``, ``zoo_fleet_router_retries_total``,
  ``zoo_fleet_deploy_fanout_seconds``).  With a tracer installed every
  routed request carries a span with ``route_pick`` / ``worker_call``
  phases and a ``worker`` label.

A restarted worker comes back BLANK: the supervisor's ``on_worker_up``
hook replays the current version set onto it (warm from store) before
the router routes any traffic at it.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ...observability import aggregate as _aggregate
from ...observability import trace as _trace
from ...observability.log import get_logger
from ...observability.metrics import (Family, parse_prometheus_text,
                                      render_prometheus)
from ..errors import ServingError
from . import artifact, protocol
from .supervisor import FleetSupervisor

_slog = get_logger("zoo.serving.fleet.router")

EXECSTORE_SUBDIR = "execstore"


class WorkerUnavailable(ServingError):
    """No live, routable worker could take the request (whole plane
    restarting or dead).  503: back off and retry."""

    http_status = 503


class _Handle:
    """Router-side view of one worker slot: endpoint + connection pool
    + the outstanding-work count the scheduler reads."""

    def __init__(self, rank: int):
        self.rank = rank
        self.port: Optional[int] = None
        self.routable = False
        self.outstanding = 0
        # the pool is GENERATION-stamped: drop_conns bumps the
        # generation, so an exchange that COMPLETED while straddling a
        # worker death (reply buffered before the kill) cannot return
        # its dead connection into a pool that was already cleaned
        self.generation = 0
        self.conns: List[Tuple[int, socket.socket]] = []
        self.lock = threading.Lock()  # pool only

    def take_conn(self, timeout: float) -> Tuple[socket.socket, int]:
        with self.lock:
            if self.conns:
                return self.conns.pop()[1], self.generation
            port, gen = self.port, self.generation
        if port is None:
            raise ConnectionError(f"worker {self.rank} has no endpoint")
        s = socket.create_connection(("127.0.0.1", port),
                                     timeout=timeout)
        s.settimeout(timeout)
        return s, gen

    def put_conn(self, conn: socket.socket, gen: int) -> None:
        with self.lock:
            if gen == self.generation:
                self.conns.append((gen, conn))
                return
        try:  # stale generation: the endpoint it reaches is gone
            conn.close()
        except OSError:
            pass

    def drop_conns(self) -> None:
        with self.lock:
            conns, self.conns = self.conns, []
            self.generation += 1
        for _, c in conns:
            try:
                c.close()
            except OSError:
                pass


class FleetRouter:
    """The fleet control plane (module docstring).

    ``share_dir`` holds the deploy artifacts and (unless the caller
    points ``ZOO_EXECSTORE_DIR`` elsewhere via ``env``) the shared
    execstore.  ``registry_kwargs`` configure every worker's
    ``ModelRegistry`` identically — identical bucket/admission config
    is what makes outputs bit-identical and fingerprints shared."""

    def __init__(self, share_dir: str, n_workers: int = 2, *,
                 run_dir: Optional[str] = None,
                 registry_kwargs: Optional[dict] = None,
                 fake: bool = False,
                 env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 2, restart_backoff: float = 0.5,
                 watchdog_sec: float = 0.0,
                 call_timeout_s: float = 120.0,
                 tracer=None):
        self.share_dir = os.path.abspath(share_dir)
        os.makedirs(self.share_dir, exist_ok=True)
        self.call_timeout_s = call_timeout_s
        self.tracer = tracer
        worker_env = dict(env or {})
        if not fake:
            worker_env.setdefault(
                "ZOO_EXECSTORE_DIR",
                os.path.join(self.share_dir, EXECSTORE_SUBDIR))
        import json as _json
        self.supervisor = FleetSupervisor(
            n_workers,
            run_dir or os.path.join(self.share_dir, "run"),
            self.share_dir, fake=fake,
            registry_json=(_json.dumps(registry_kwargs)
                           if registry_kwargs else None),
            env=worker_env, max_restarts=max_restarts,
            restart_backoff=restart_backoff,
            watchdog_sec=watchdog_sec,
            on_worker_up=self._on_worker_up,
            on_worker_down=self._on_worker_down)
        self.handles = [_Handle(r) for r in range(n_workers)]
        self._lock = threading.Lock()       # scheduling + version set
        self._active: Dict[str, int] = {}   # model -> active version
        self._next_version: Dict[str, int] = {}
        self._rr = 0
        self._retries_total = 0
        self._req_seq = 0
        self._fanouts: Dict[Tuple[str, int], float] = {}
        self.last_fanout: List[Dict[str, Any]] = []
        # rank -> the replay-activation reports of its LAST (re)start
        # (the kill drill reads the restarted worker's compile count
        # here: warm-from-store must be zero, cross-process)
        self.replays: Dict[int, List[Dict[str, Any]]] = {}
        self._reviving: set = set()  # ranks with a live revival probe
        self._closed = False

    # ---- lifecycle ----
    def start(self, timeout: float = 120.0) -> None:
        """Start the worker plane and wait until every worker is
        routable (raises on timeout — a fleet that cannot field its
        workers should fail loudly at startup, not shed mysteriously
        later)."""
        self.supervisor.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(h.routable for h in self.handles):
                return
            if any(w.state == "dead" for w in self.supervisor.workers):
                break
            time.sleep(0.05)
        states = self.supervisor.states()
        self.supervisor.stop()
        raise RuntimeError(
            f"fleet failed to start within {timeout}s: {states}")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.supervisor.stop()
        for h in self.handles:
            h.drop_conns()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- supervisor hooks (monitor thread) ----
    def _on_worker_down(self, rank: int) -> None:
        h = self.handles[rank]
        h.routable = False
        h.port = None
        h.drop_conns()

    def _on_worker_up(self, rank: int, port: int,
                      incarnation: int) -> None:
        """A (re)started worker is blank: replay the current version
        set onto it — warm from the shared store, so this is
        milliseconds — BEFORE marking it routable."""
        h = self.handles[rank]
        h.drop_conns()
        h.port = port
        with self._lock:
            replay = sorted(self._active.items())
        reports = []
        for model, version in replay:
            resp = self._call(h, {"op": "activate", "model": model,
                                  "version": version})
            reports.append({"model": model, **resp["result"]})
            _slog.info("fleet_replay_activate", rank=rank, model=model,
                       version=version,
                       compiles=resp["result"]["compiles"],
                       warm_ms=resp["result"]["warm_ms"])
        self.replays[rank] = reports
        h.routable = True

    # ---- wire calls ----
    def _call(self, h: _Handle, req: Dict[str, Any]) -> Dict[str, Any]:
        """One request/reply exchange with one worker on a pooled
        connection.  Any transport-level failure closes the connection
        and surfaces as ConnectionError (the worker-death signal);
        a structured error envelope raises the reconstructed serving
        exception."""
        with self._lock:
            self._req_seq += 1
            req = {**req, "id": self._req_seq}
        conn = None
        try:
            # take_conn INSIDE the normalizing try: a connect that
            # hangs raises TimeoutError, which is an OSError but NOT
            # a ConnectionError — without normalization a wedged
            # accept loop would escape the retry-on-sibling contract
            conn, gen = h.take_conn(self.call_timeout_s)
            protocol.send_frame(conn, req)
            resp = protocol.recv_frame(conn)
        except (OSError, protocol.FrameError) as e:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            raise ConnectionError(
                f"worker {h.rank} failed mid-request: "
                f"{type(e).__name__}: {e}") from e
        if resp is None or resp.get("id") != req["id"]:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(
                f"worker {h.rank} hung up mid-request")
        h.put_conn(conn, gen)
        if not resp.get("ok"):
            raise protocol.decode_error(resp.get("error") or {})
        return resp

    def _pick(self, exclude: Optional[int] = None) -> _Handle:
        """Least-outstanding-work over routable workers, ties rotated
        (pure min-index would camp light traffic on worker 0)."""
        with self._lock:
            live = [h for h in self.handles
                    if h.routable and h.rank != exclude]
            if not live:
                raise WorkerUnavailable(
                    "no live fleet worker available",
                    states=self.supervisor.states())
            best = min(h.outstanding for h in live)
            candidates = [h for h in live if h.outstanding == best]
            h = candidates[self._rr % len(candidates)]
            self._rr += 1
            h.outstanding += 1
            return h

    def _release(self, h: _Handle) -> None:
        with self._lock:
            h.outstanding -= 1

    def _schedule_revival(self, h: _Handle) -> None:
        """Router-side unrouting must be recoverable without a worker
        restart: a DETACHED probe (PR 6's health re-probe discipline —
        never inline on the request path) pings the worker with
        backoff and restores it on success.  A worker that really
        died fails every ping until the supervisor's incident path
        takes over (``on_worker_down`` nulls the port, which ends the
        probe; the restart's ``on_worker_up`` replay re-routes it)."""
        with self._lock:
            if h.rank in self._reviving:
                return
            self._reviving.add(h.rank)
        threading.Thread(target=self._revive, args=(h,), daemon=True,
                         name=f"fleet-revive-{h.rank}").start()

    def _revive(self, h: _Handle) -> None:
        try:
            delay = 0.2
            deadline = time.monotonic() + max(self.call_timeout_s,
                                              30.0)
            while time.monotonic() < deadline and not self._closed:
                if (self.supervisor.worker(h.rank).state != "live"
                        or h.port is None):
                    return  # the supervisor owns this incident now
                try:
                    self._call(h, {"op": "ping"})
                except (ConnectionError, ServingError):
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
                    continue
                h.routable = True
                _slog.info("fleet_worker_revived", rank=h.rank)
                return
        finally:
            with self._lock:
                self._reviving.discard(h.rank)

    def _route_call(self, req: Dict[str, Any], span=None
                    ) -> Dict[str, Any]:
        """The routed data path (a zoolint hot entry): pick, call,
        and on a worker death retry ONCE on a sibling.  The failed
        worker is marked unroutable immediately; a detached revival
        probe then pings it — a worker that actually died stays out
        until the supervisor restarts + replays it, but a TRANSIENT
        failure (one slow request tripping the call timeout on a
        healthy worker) costs it the rotation only until the next
        successful ping, never forever."""
        if span is not None:
            span.phase_start("route_pick")
        h = self._pick()
        if span is not None:
            span.set_label("worker", h.rank)
            span.phase_start("worker_call")
        try:
            return self._call(h, req)
        except ConnectionError:
            h.routable = False
            h.drop_conns()
            self._schedule_revival(h)
            with self._lock:
                self._retries_total += 1
            _slog.warning("fleet_retry_on_sibling", failed=h.rank,
                          op=req.get("op"))
            if span is not None:
                span.set_label("retried", True)
            h2 = self._pick(exclude=h.rank)
            if span is not None:
                span.set_label("worker", h2.rank)
            try:
                return self._call(h2, req)
            finally:
                self._release(h2)
        finally:
            self._release(h)

    # ---- serving surface ----
    def predict(self, model: str, inputs,
                deadline_ms: Optional[float] = None,
                priority_class: Optional[str] = None):
        out, _ = self.predict_ex(model, inputs,
                                 deadline_ms=deadline_ms,
                                 priority_class=priority_class)
        return out

    def predict_ex(self, model: str, inputs,
                   deadline_ms: Optional[float] = None,
                   trace_id: Optional[str] = None,
                   priority_class: Optional[str] = None
                   ) -> Tuple[Any, Dict[str, Any]]:
        return self._serve_ex(
            {"op": "predict", "model": model,
             "inputs": protocol.encode_value(inputs)},
            model, "predict", deadline_ms, trace_id, priority_class)

    def generate_ex(self, model: str, prompt_ids, max_new_tokens: int,
                    deadline_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority_class: Optional[str] = None,
                    eos_id: Optional[int] = None,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None, seed: int = 0
                    ) -> Tuple[Any, Dict[str, Any]]:
        # sampling params ride the envelope as json-safe scalars
        # (validated worker-side by the engine, so a bad value comes
        # back as the concrete ValueError, not a dead connection);
        # determinism contract: same (prompt, sampling, seed) on any
        # worker == the single-process registry, bit-exact
        return self._serve_ex(
            {"op": "generate",
             "prompt_ids": protocol.encode_value(prompt_ids),
             "model": model, "max_new_tokens": int(max_new_tokens),
             "eos_id": eos_id, "temperature": float(temperature),
             "top_k": None if top_k is None else int(top_k),
             "top_p": None if top_p is None else float(top_p),
             "seed": int(seed)},
            model, "generate", deadline_ms, trace_id, priority_class)

    def _serve_ex(self, req: Dict[str, Any], model: str, op: str,
                  deadline_ms, trace_id, priority_class
                  ) -> Tuple[Any, Dict[str, Any]]:
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        if priority_class is not None:
            req["priority_class"] = priority_class
        tracer = self.tracer
        span = (tracer.start_span(op, trace_id=trace_id, model=model)
                if tracer is not None else None)
        if span is not None:
            req["trace_id"] = span.trace_id
        elif trace_id is not None:
            req["trace_id"] = trace_id
        try:
            with _trace.activate(span):
                resp = self._route_call(req, span=span)
        except BaseException as e:
            if span is not None:
                span.set_label("error", type(e).__name__)
            raise
        finally:
            if span is not None:
                span.finish()
        info = dict(resp.get("info") or {})
        if span is not None:
            info["request_id"] = span.trace_id
        return protocol.decode_value(resp.get("result")), info

    # ---- deploy / fan-out ----
    def deploy(self, model: str, params: Optional[Dict[str, Any]],
               builder: str, builder_args: Optional[dict] = None,
               warmup_shapes=None, version: Optional[int] = None,
               deploy_kwargs: Optional[dict] = None
               ) -> Dict[str, Any]:
        """Fleet deploy: persist the artifact once, then activate it
        on every worker one at a time (rolling, warm-before-swap per
        worker).  Returns the fan-out report ``{"version",
        "fanout_s", "activations": [{rank, compiles, warm_ms,
        error?}, ...]}``.  A worker that dies mid-fan-out is skipped —
        its restart replays the new version from the share."""
        # auto-versioning is seeded from the COMMITTED artifacts on
        # disk, not in-memory state alone: a restarted router must
        # never reuse a version number and overwrite an artifact
        # long-running workers still replay from (the spec rename is
        # the commit — committed artifacts are immutable)
        disk_floor = (max(artifact.versions(self.share_dir, model),
                          default=0) + 1 if version is None else 0)
        with self._lock:
            if version is None:
                version = max(self._next_version.get(model, 1),
                              disk_floor)
            self._next_version[model] = max(
                self._next_version.get(model, 1), version + 1)
        artifact.publish(
            self.share_dir, model, version, params,
            {"builder": builder, "args": builder_args or {},
             "warmup_shapes": (list(warmup_shapes)
                               if warmup_shapes is not None else None),
             "deploy_kwargs": deploy_kwargs or {}})
        # the version set updates BEFORE fan-out so a worker
        # restarting mid-deploy replays the NEW version (activation is
        # version-pinned and idempotent, double-activation is safe)
        with self._lock:
            self._active[model] = version
        t0 = time.perf_counter()
        activations: List[Dict[str, Any]] = []
        for h in list(self.handles):
            if not (h.routable or h.port is not None):
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            ta = time.perf_counter()
            try:
                resp = self._call(h, {"op": "activate", "model": model,
                                      "version": version})
                entry.update(resp["result"])
            except (ConnectionError, ServingError) as e:
                # dead worker: its replacement replays from the share.
                # A structured deploy failure is recorded, not raised
                # mid-fan-out — the report carries the verdict.
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_activate_failed", rank=h.rank,
                            model=model, version=version,
                            error=entry["error"])
            entry["t_start"] = round(ta - t0, 6)
            entry["t_end"] = round(time.perf_counter() - t0, 6)
            activations.append(entry)
        fanout_s = round(time.perf_counter() - t0, 6)
        with self._lock:
            self._fanouts[(model, version)] = fanout_s
        self.last_fanout = activations
        _slog.info("fleet_deploy_fanout", model=model, version=version,
                   fanout_s=fanout_s,
                   workers=[a["rank"] for a in activations])
        return {"version": version, "fanout_s": fanout_s,
                "activations": activations}

    def promote(self, model: str) -> Dict[str, Any]:
        """Fan out a canary promote to every routable worker —
        deploy's per-worker error discipline: one dead worker is
        recorded and skipped (its replacement replays the PROMOTED
        version set), never an aborted half-promoted fleet."""
        results = []
        promoted: Optional[int] = None
        for h in list(self.handles):
            if not h.routable:
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            try:
                resp = self._call(h, {"op": "promote", "model": model})
                entry.update(resp["result"])
                promoted = entry["version"]
                # _active updates at the FIRST success (deploy's
                # discipline): a worker restarting mid-promote must
                # replay the promoted version, not the one it died on
                with self._lock:
                    self._active[model] = promoted
            except (ConnectionError, ServingError) as e:
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_promote_failed", rank=h.rank,
                            model=model, error=entry["error"])
            results.append(entry)
        return {"version": promoted, "activations": results}

    def undeploy(self, model: str) -> Dict[str, Any]:
        """Fan out an undeploy to every routable worker and RETIRE the
        model's fleet-level series: the per-(model, version) fan-out
        gauge and the active-version map are dropped, so a density
        fleet cycling hundreds of models does not grow the router
        scrape (or its memory) one dead series per deploy forever.
        Committed artifacts stay on the share (undeploy retires the
        SERVING state, not the deploy history); per-worker error
        discipline matches deploy/promote — a dead worker's
        replacement simply never replays the retired model."""
        results = []
        for h in list(self.handles):
            if not h.routable:
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            try:
                resp = self._call(h, {"op": "undeploy",
                                      "model": model})
                entry.update(resp["result"])
            except (ConnectionError, ServingError) as e:
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_undeploy_failed", rank=h.rank,
                            model=model, error=entry["error"])
            results.append(entry)
        with self._lock:
            self._active.pop(model, None)
            self._next_version.pop(model, None)
            for key in [k for k in self._fanouts if k[0] == model]:
                self._fanouts.pop(key, None)
        _slog.info("fleet_undeploy", model=model,
                   workers=[r["rank"] for r in results])
        return {"model": model, "activations": results}

    def ping(self, rank: int) -> Dict[str, Any]:
        return self._call(self.handles[rank],
                          {"op": "ping"})["result"]

    # ---- observability ----
    def families(self) -> List[Family]:
        states = self.supervisor.states()
        with self._lock:
            retries = self._retries_total
            fanouts = dict(self._fanouts)
        fams = [
            Family("gauge", "zoo_fleet_workers",
                   "fleet workers by supervision state",
                   [({"state": s}, n) for s, n in sorted(states.items())]),
            Family("counter", "zoo_fleet_router_retries_total",
                   "requests retried on a sibling after a worker "
                   "death mid-request", [({}, retries)]),
        ]
        if fanouts:
            fams.append(Family(
                "gauge", "zoo_fleet_deploy_fanout_seconds",
                "wall seconds of the last activation fan-out per "
                "(model, version)",
                [({"model": m, "version": str(v)}, s)
                 for (m, v), s in sorted(fanouts.items())]))
        return fams

    def metrics_text(self) -> str:
        """The fleet scrape: every live worker's exposition merged
        through the pod aggregator (rank labels + counter fleet
        totals), the router's own families appended."""
        pairs = []
        for h in list(self.handles):
            if not h.routable:
                continue
            try:
                resp = self._call(h, {"op": "metrics"})
            except (ConnectionError, ServingError):
                continue  # a worker dying mid-scrape skips one rank
            pairs.append((h.rank,
                          parse_prometheus_text(resp["result"]["text"])))
        fams = _aggregate.merge_snapshots(pairs)
        fams.extend(self.families())
        return render_prometheus(fams)

    def states(self) -> Dict[str, int]:
        return self.supervisor.states()

    @property
    def retries_total(self) -> int:
        with self._lock:
            return self._retries_total
