"""Fleet router: the thin control plane in front of the worker plane.

Speaks the same admission/priority serving envelope OUTWARD that every
worker speaks inward (``predict_ex``/``generate_ex`` with deadline,
trace id, priority class, structured ``Overloaded``/
``DeadlineExceeded`` errors reconstructed concretely), and owns three
fleet-only jobs:

* **Scheduling** — least-outstanding-work across live workers, ties
  rotated (the ReplicaSet scheduler generalized across processes: one
  outstanding-count per worker instead of one in-flight slot per
  device), WEIGHTED BY RESIDENCY (PR 16): workers piggyback their
  pager residency on every reply, and a request for a model some
  worker already holds on device pays an ``affinity_penalty`` to land
  anywhere else — N per-worker pagers behave as ONE fleet cache with
  effective capacity N×budget, and the penalty (not a hard pin) means
  a hot resident worker still spills to a sibling under load.
  Outcomes are counted in ``zoo_fleet_affinity_total{outcome=
  hit|miss|cold}``.  A connection-level failure mid-request — the
  worker died under it — is retried ONCE on a sibling, exactly like
  replica fault tolerance retries a crashed device dispatch
  in-process; structured serving errors are real rejections and are
  NEVER retried.
* **Deploy fan-out** — ``deploy()`` persists the artifact (weights +
  spec) on the share ONCE, then activates the version on each worker
  ONE AT A TIME; every activation is the worker's own
  warm-before-swap, so the rolling upgrade never takes a worker out
  of service.  The first activation pays the compiles and populates
  the shared execstore; every later worker (and every restarted one)
  warms from the store in milliseconds with zero compiles — the
  instant-fleet-deploy promise, finally gated cross-process.
* **Observability** — ``metrics_text()`` scrapes every live worker
  and merges the expositions through the pod aggregator (workers are
  ranks: every sample gains a ``rank`` label, counters sum to a
  rank-less fleet total), plus the router's own families
  (``zoo_fleet_workers{state}``, ``zoo_fleet_router_retries_total``,
  ``zoo_fleet_deploy_fanout_seconds``).  With a tracer installed every
  routed request carries a span with ``route_pick`` / ``worker_call``
  phases and a ``worker`` label.

Fleet v2 additions (PR 16):

* **Binary wire** — each fresh connection negotiates the v2 binary
  payload encoding with a ``hello`` (old workers answer ``unknown
  op`` → that connection stays on JSON); negotiated predict/generate
  requests and replies then carry ndarrays as raw out-of-band buffers
  (:func:`protocol.encode_binary`), decoded zero-copy.  Per-direction
  per-encoding byte counts land in
  ``zoo_fleet_wire_bytes_total{direction,encoding}``.
* **Cross-process coalescing** — with ``coalesce_ms > 0``, concurrent
  ``predict`` calls for the same (model, priority, deadline, dtype,
  trailing-shape) merge into ONE wire request: the first caller
  becomes the leader, waits the window, concatenates rider rows on
  axis 0, sends one frame, and splits the reply — PR 2's worker-side
  coalescer composes through the fleet instead of being defeated by
  one-row frames.
* **Elastic pool** — ``set_pool_size`` grows (spawn/revive + the
  on_worker_up execstore replay = zero-compile warm-up) or shrinks
  the worker plane; scale-down unroutes the victim, DRAINS its
  in-flight work, then retires it through the supervisor (no
  postmortem, no restart).  :func:`fleet_autoscaler` points PR 6's
  ``Autoscaler`` at this: queue-depth/latency-EWMA signals in,
  ``set_pool_size`` out.

A restarted worker comes back BLANK: the supervisor's ``on_worker_up``
hook replays the current version set onto it (warm from store) before
the router routes any traffic at it.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ...observability import aggregate as _aggregate
from ...observability import trace as _trace
from ...observability import tracefleet
from ...observability.log import get_logger
from ...observability.metrics import (Family, parse_prometheus_text,
                                      render_prometheus)
from ..errors import ServingError, WorkerUnavailable
from . import artifact, protocol
from .supervisor import FleetSupervisor

_slog = get_logger("zoo.serving.fleet.router")

EXECSTORE_SUBDIR = "execstore"


class _Handle:
    """Router-side view of one worker slot: endpoint + connection pool
    + the outstanding-work count the scheduler reads."""

    def __init__(self, rank: int):
        self.rank = rank
        self.port: Optional[int] = None
        self.routable = False
        # scale-down drain latch: set before draining so neither the
        # scheduler nor a racing revival probe routes new work at a
        # worker on its way out
        self.retiring = False
        self.outstanding = 0
        # residency piggyback state (PR 16): the models this worker
        # reported resident on its LAST reply/ping, and its own
        # in-flight count at that moment.  Whole-object swaps under
        # the GIL — readers see the old set or the new one, never a
        # torn set — so the scheduler reads these lock-free.
        self.resident: frozenset = frozenset()
        self.worker_inflight = 0
        # the pool is GENERATION-stamped: drop_conns bumps the
        # generation, so an exchange that COMPLETED while straddling a
        # worker death (reply buffered before the kill) cannot return
        # its dead connection into a pool that was already cleaned.
        # Each pooled conn also carries its NEGOTIATED wire version —
        # negotiation is per-connection, paid once at connect.
        self.generation = 0
        self.conns: List[Tuple[int, socket.socket, int]] = []
        self.lock = threading.Lock()  # pool only

    def take_conn(self, timeout: float
                  ) -> Tuple[socket.socket, int, Optional[int]]:
        """A pooled ``(conn, generation, wire)`` — ``wire`` is None
        for a FRESH connection (the caller negotiates and passes the
        verdict back through :meth:`put_conn`)."""
        with self.lock:
            if self.conns:
                gen, conn, wire = self.conns.pop()
                return conn, gen, wire
            port, gen = self.port, self.generation
        if port is None:
            raise ConnectionError(f"worker {self.rank} has no endpoint")
        s = socket.create_connection(("127.0.0.1", port),
                                     timeout=timeout)
        s.settimeout(timeout)
        return s, gen, None

    def put_conn(self, conn: socket.socket, gen: int,
                 wire: int) -> None:
        with self.lock:
            if gen == self.generation:
                self.conns.append((gen, conn, wire))
                return
        try:  # stale generation: the endpoint it reaches is gone
            conn.close()
        except OSError:
            pass

    def drop_conns(self) -> None:
        with self.lock:
            conns, self.conns = self.conns, []
            self.generation += 1
        for _, c, _ in conns:
            try:
                c.close()
            except OSError:
                pass


class _Batch:
    """One open cross-process coalescing batch: the FIRST caller for
    a key is the leader (it waits the window, concatenates, sends one
    wire request, splits the reply); later callers are riders parked
    on ``done``.  Rows/sizes are appended under the router's coalesce
    lock; results/error are written by the leader before ``done``
    fires."""

    def __init__(self):
        self.rows: List[Any] = []
        self.sizes: List[int] = []
        self.total = 0
        self.closed = False
        self.done = threading.Event()
        self.result = None
        self.info: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None


class FleetRouter:
    """The fleet control plane (module docstring).

    ``share_dir`` holds the deploy artifacts and (unless the caller
    points ``ZOO_EXECSTORE_DIR`` elsewhere via ``env``) the shared
    execstore.  ``registry_kwargs`` configure every worker's
    ``ModelRegistry`` identically — identical bucket/admission config
    is what makes outputs bit-identical and fingerprints shared."""

    def __init__(self, share_dir: str, n_workers: int = 2, *,
                 run_dir: Optional[str] = None,
                 registry_kwargs: Optional[dict] = None,
                 fake: bool = False,
                 env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 2, restart_backoff: float = 0.5,
                 watchdog_sec: float = 0.0,
                 call_timeout_s: float = 120.0,
                 wire: str = "binary",
                 affinity_penalty: int = 4,
                 coalesce_ms: float = 0.0,
                 coalesce_rows: int = 64,
                 tracer=None):
        self.share_dir = os.path.abspath(share_dir)
        os.makedirs(self.share_dir, exist_ok=True)
        self.call_timeout_s = call_timeout_s
        # "binary" negotiates the v2 wire per connection (old/pinned
        # workers degrade that connection to JSON); "json" skips the
        # hello entirely — the A/B lever the fleet drill measures with
        self.wire = wire
        # affinity: a non-resident worker's score is outstanding +
        # penalty, so residency wins until the resident worker is
        # ~penalty requests deeper than a sibling — a soft pin that
        # load can override (hard pinning would turtle one worker)
        self.affinity_penalty = affinity_penalty
        # cross-process coalescing window (0 = off): concurrent
        # same-key predicts merge into one wire request
        self.coalesce_ms = coalesce_ms
        self.coalesce_rows = coalesce_rows
        self.tracer = tracer
        worker_env = dict(env or {})
        if not fake:
            worker_env.setdefault(
                "ZOO_EXECSTORE_DIR",
                os.path.join(self.share_dir, EXECSTORE_SUBDIR))
        import json as _json
        self.supervisor = FleetSupervisor(
            n_workers,
            run_dir or os.path.join(self.share_dir, "run"),
            self.share_dir, fake=fake,
            registry_json=(_json.dumps(registry_kwargs)
                           if registry_kwargs else None),
            env=worker_env, max_restarts=max_restarts,
            restart_backoff=restart_backoff,
            watchdog_sec=watchdog_sec,
            on_worker_up=self._on_worker_up,
            on_worker_down=self._on_worker_down)
        self.handles = [_Handle(r) for r in range(n_workers)]
        self._lock = threading.Lock()       # scheduling + version set
        self._active: Dict[str, int] = {}   # model -> active version
        self._next_version: Dict[str, int] = {}
        self._rr = 0
        self._retries_total = 0
        self._req_seq = 0
        # v2 telemetry: affinity outcomes, per-(direction, encoding)
        # wire bytes, and a served-latency EWMA (the autoscaler's
        # pressure signal alongside queue depth)
        self._affinity = {"hit": 0, "miss": 0, "cold": 0}
        self._wire_bytes: Dict[Tuple[str, str], int] = {}
        self._ewma_ms: Optional[float] = None
        # coalescer: one open batch per key, leader/rider protocol
        self._co_lock = threading.Lock()
        self._co_open: Dict[Any, "_Batch"] = {}
        self._fanouts: Dict[Tuple[str, int], float] = {}
        self.last_fanout: List[Dict[str, Any]] = []
        # rank -> the replay-activation reports of its LAST (re)start
        # (the kill drill reads the restarted worker's compile count
        # here: warm-from-store must be zero, cross-process)
        self.replays: Dict[int, List[Dict[str, Any]]] = {}
        self._reviving: set = set()  # ranks with a live revival probe
        self._closed = False

    # ---- lifecycle ----
    def start(self, timeout: float = 120.0) -> None:
        """Start the worker plane and wait until every worker is
        routable (raises on timeout — a fleet that cannot field its
        workers should fail loudly at startup, not shed mysteriously
        later)."""
        self.supervisor.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(h.routable for h in self.handles):
                return
            if any(w.state == "dead" for w in self.supervisor.workers):
                break
            time.sleep(0.05)
        states = self.supervisor.states()
        self.supervisor.stop()
        raise RuntimeError(
            f"fleet failed to start within {timeout}s: {states}")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.supervisor.stop()
        for h in self.handles:
            h.drop_conns()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- supervisor hooks (monitor thread) ----
    def _on_worker_down(self, rank: int) -> None:
        h = self.handles[rank]
        h.routable = False
        h.port = None
        h.drop_conns()

    def _on_worker_up(self, rank: int, port: int,
                      incarnation: int) -> None:
        """A (re)started worker is blank: replay the current version
        set onto it — warm from the shared store, so this is
        milliseconds — BEFORE marking it routable."""
        h = self.handles[rank]
        h.drop_conns()
        h.port = port
        with self._lock:
            replay = sorted(self._active.items())
        reports = []
        for model, version in replay:
            resp = self._call(h, {"op": "activate", "model": model,
                                  "version": version})
            reports.append({"model": model, **resp["result"]})
            _slog.info("fleet_replay_activate", rank=rank, model=model,
                       version=version,
                       compiles=resp["result"]["compiles"],
                       warm_ms=resp["result"]["warm_ms"])
        self.replays[rank] = reports
        h.routable = True

    # ---- wire calls ----
    def _negotiate(self, conn: socket.socket, rank: int) -> int:
        """Per-connection wire handshake: one ``hello`` exchange.  An
        old worker (or one pinned with ``ZOO_FLEET_WIRE=json``)
        answers without a binary verdict and the connection stays on
        the v1 JSON wire — mixed fleets interoperate per-connection.
        Transport failures propagate (the caller's normalizing try
        owns them)."""
        if self.wire != "binary":
            return protocol.WIRE_JSON
        protocol.send_frame(conn, {"op": "hello", "id": 0,
                                   "wire": protocol.WIRE_BINARY})
        resp = protocol.recv_frame(conn)
        if resp is None:
            raise protocol.FrameError(
                f"worker {rank} hung up during wire negotiation")
        if (resp.get("ok")
                and isinstance(resp.get("result"), dict)
                and resp["result"].get("wire")
                == protocol.WIRE_BINARY):
            return protocol.WIRE_BINARY
        return protocol.WIRE_JSON

    def _count_wire(self, direction: str, encoding: str,
                    nbytes: int) -> None:
        with self._lock:
            key = (direction, encoding)
            self._wire_bytes[key] = self._wire_bytes.get(key, 0) \
                + nbytes

    def _call(self, h: _Handle, req: Dict[str, Any]) -> Dict[str, Any]:
        """One request/reply exchange with one worker on a pooled
        connection.  Any transport-level failure closes the connection
        and surfaces as ConnectionError (the worker-death signal);
        a structured error envelope raises the reconstructed serving
        exception.  Serve-op payloads ride the negotiated wire
        (binary: ndarrays as raw out-of-band buffers, zero-copy on
        decode); control ops stay JSON — no arrays, and a readable
        envelope is worth more than the few bytes.  Every reply's
        ``load`` piggyback refreshes this handle's residency view."""
        with self._lock:
            self._req_seq += 1
            req = {**req, "id": self._req_seq}
        conn = None
        try:
            # take_conn INSIDE the normalizing try: a connect that
            # hangs raises TimeoutError, which is an OSError but NOT
            # a ConnectionError — without normalization a wedged
            # accept loop would escape the retry-on-sibling contract
            conn, gen, wire = h.take_conn(self.call_timeout_s)
            if wire is None:
                wire = self._negotiate(conn, h.rank)
            binary = (wire == protocol.WIRE_BINARY
                      and req.get("op") in ("predict", "generate"))
            n_tx = protocol.send_envelope(conn, req, binary=binary)
            got = protocol.recv_envelope(conn)
        except (OSError, protocol.FrameError) as e:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            raise ConnectionError(
                f"worker {h.rank} failed mid-request: "
                f"{type(e).__name__}: {e}") from e
        self._count_wire("tx", "binary" if binary else "json", n_tx)
        if got is not None:
            self._count_wire("rx", got[2], got[1])
        resp = got[0] if got is not None else None
        if resp is None or resp.get("id") != req["id"]:
            try:
                conn.close()
            except OSError:
                pass
            raise ConnectionError(
                f"worker {h.rank} hung up mid-request")
        h.put_conn(conn, gen, wire)
        load = resp.get("load")
        if isinstance(load, dict):
            # whole-object swaps, read lock-free by the scheduler
            h.resident = frozenset(load.get("r") or ())
            h.worker_inflight = int(load.get("o") or 0)
        if not resp.get("ok"):
            raise protocol.decode_error(resp.get("error") or {})
        return resp

    def _pick(self, exclude: Optional[int] = None,
              model: Optional[str] = None,
              count: bool = True) -> _Handle:
        """Least-outstanding-work over routable workers, ties rotated
        (pure min-index would camp light traffic on worker 0),
        residency-weighted when a model is named: a worker NOT
        holding the model scores ``outstanding + affinity_penalty``,
        so requests follow residency until load outweighs the fault
        cost.  Outcomes: ``hit`` — a resident worker chosen; ``miss``
        — someone holds it but load sent us elsewhere; ``cold`` — no
        live worker holds it (somebody must fault).  The retry-on-
        sibling re-pick passes ``count=False`` — one request, one
        outcome."""
        with self._lock:
            live = [h for h in self.handles
                    if h.routable and not h.retiring
                    and h.rank != exclude]
            if not live:
                raise WorkerUnavailable(
                    "no live fleet worker available",
                    states=self.supervisor.states())
            if model is None:
                score = {h.rank: h.outstanding for h in live}
            else:
                score = {h.rank: h.outstanding
                         + (0 if model in h.resident
                            else self.affinity_penalty)
                         for h in live}
            best = min(score.values())
            candidates = [h for h in live if score[h.rank] == best]
            h = candidates[self._rr % len(candidates)]
            self._rr += 1
            h.outstanding += 1
            if model is not None and count:
                if model in h.resident:
                    self._affinity["hit"] += 1
                elif any(model in x.resident for x in live):
                    self._affinity["miss"] += 1
                else:
                    self._affinity["cold"] += 1
            return h

    def _release(self, h: _Handle) -> None:
        with self._lock:
            h.outstanding -= 1

    def _schedule_revival(self, h: _Handle) -> None:
        """Router-side unrouting must be recoverable without a worker
        restart: a DETACHED probe (PR 6's health re-probe discipline —
        never inline on the request path) pings the worker with
        backoff and restores it on success.  A worker that really
        died fails every ping until the supervisor's incident path
        takes over (``on_worker_down`` nulls the port, which ends the
        probe; the restart's ``on_worker_up`` replay re-routes it)."""
        with self._lock:
            if h.rank in self._reviving:
                return
            self._reviving.add(h.rank)
        threading.Thread(target=self._revive, args=(h,), daemon=True,
                         name=f"fleet-revive-{h.rank}").start()

    def _revive(self, h: _Handle) -> None:
        try:
            delay = 0.2
            deadline = time.monotonic() + max(self.call_timeout_s,
                                              30.0)
            while time.monotonic() < deadline and not self._closed:
                if (self.supervisor.worker(h.rank).state != "live"
                        or h.port is None):
                    return  # the supervisor owns this incident now
                try:
                    self._call(h, {"op": "ping"})
                except (ConnectionError, ServingError):
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
                    continue
                if not h.retiring:
                    h.routable = True
                _slog.info("fleet_worker_revived", rank=h.rank)
                return
        finally:
            with self._lock:
                self._reviving.discard(h.rank)

    def _route_call(self, req: Dict[str, Any], span=None,
                    model: Optional[str] = None) -> Dict[str, Any]:
        """The routed data path (a zoolint hot entry): pick, call,
        and on a worker death retry ONCE on a sibling.  The failed
        worker is marked unroutable immediately; a detached revival
        probe then pings it — a worker that actually died stays out
        until the supervisor restarts + replays it, but a TRANSIENT
        failure (one slow request tripping the call timeout on a
        healthy worker) costs it the rotation only until the next
        successful ping, never forever."""
        if span is not None:
            span.phase_start("route_pick")
        h = self._pick(model=model)
        if span is not None:
            span.set_label("worker", h.rank)
            span.phase_start("worker_call")
        try:
            resp = self._call(h, req)
            if span is not None:
                # inline stitch: nest the worker's piggybacked span
                # summary under this worker_call occurrence
                tracefleet.nest_summary(span, resp.get("trace"))
            return resp
        except ConnectionError:
            h.routable = False
            h.drop_conns()
            self._schedule_revival(h)
            with self._lock:
                self._retries_total += 1
            _slog.warning("fleet_retry_on_sibling", failed=h.rank,
                          op=req.get("op"))
            if span is not None:
                span.set_label("retried", True)
                # the sibling leg is its OWN worker_call occurrence:
                # the stitcher attributes the failed leg (no reply,
                # no worker record) to the first occurrence and the
                # served leg to this one
                span.phase_start("worker_call")
            h2 = self._pick(exclude=h.rank, model=model, count=False)
            if span is not None:
                span.set_label("worker", h2.rank)
            try:
                resp = self._call(h2, req)
                if span is not None:
                    tracefleet.nest_summary(span, resp.get("trace"))
                return resp
            finally:
                self._release(h2)
        finally:
            self._release(h)

    # ---- serving surface ----
    def predict(self, model: str, inputs,
                deadline_ms: Optional[float] = None,
                priority_class: Optional[str] = None):
        out, _ = self.predict_ex(model, inputs,
                                 deadline_ms=deadline_ms,
                                 priority_class=priority_class)
        return out

    def predict_ex(self, model: str, inputs,
                   deadline_ms: Optional[float] = None,
                   trace_id: Optional[str] = None,
                   priority_class: Optional[str] = None
                   ) -> Tuple[Any, Dict[str, Any]]:
        # inputs stay RAW ndarrays in the request envelope — the
        # encoding decision (binary out-of-band vs JSON b64) belongs
        # to the negotiated connection at send time, not here
        if self.coalesce_ms > 0:
            import numpy as np
            x = np.asarray(inputs)
            if x.ndim >= 2:
                return self._predict_coalesced(
                    model, x, deadline_ms, trace_id, priority_class)
        return self._serve_ex(
            {"op": "predict", "model": model, "inputs": inputs},
            model, "predict", deadline_ms, trace_id, priority_class)

    def generate_ex(self, model: str, prompt_ids, max_new_tokens: int,
                    deadline_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority_class: Optional[str] = None,
                    eos_id: Optional[int] = None,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None, seed: int = 0
                    ) -> Tuple[Any, Dict[str, Any]]:
        # sampling params ride the envelope as json-safe scalars
        # (validated worker-side by the engine, so a bad value comes
        # back as the concrete ValueError, not a dead connection);
        # determinism contract: same (prompt, sampling, seed) on any
        # worker == the single-process registry, bit-exact
        return self._serve_ex(
            {"op": "generate",
             "prompt_ids": prompt_ids,
             "model": model, "max_new_tokens": int(max_new_tokens),
             "eos_id": eos_id, "temperature": float(temperature),
             "top_k": None if top_k is None else int(top_k),
             "top_p": None if top_p is None else float(top_p),
             "seed": int(seed)},
            model, "generate", deadline_ms, trace_id, priority_class)

    def _serve_ex(self, req: Dict[str, Any], model: str, op: str,
                  deadline_ms, trace_id, priority_class
                  ) -> Tuple[Any, Dict[str, Any]]:
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        if priority_class is not None:
            req["priority_class"] = priority_class
        tracer = self.tracer
        span = (tracer.start_span(op, trace_id=trace_id, model=model)
                if tracer is not None else None)
        if span is not None:
            req["trace_id"] = span.trace_id
        elif trace_id is not None:
            req["trace_id"] = trace_id
        t0 = time.perf_counter()
        try:
            with _trace.activate(span):
                resp = self._route_call(req, span=span, model=model)
        except BaseException as e:
            if span is not None:
                span.set_label("error", type(e).__name__)
            raise
        finally:
            if span is not None:
                span.finish()
        ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            # served-latency EWMA: the autoscaler's pressure signal
            self._ewma_ms = (ms if self._ewma_ms is None
                             else 0.2 * ms + 0.8 * self._ewma_ms)
        info = dict(resp.get("info") or {})
        if span is not None:
            info["request_id"] = span.trace_id
            if span.children:
                # the per-request wire+queue remainder: worker_call
                # time the nested worker legs do NOT account for
                gap = tracefleet.inline_gap_ms(span)
                if gap is not None:
                    info["fleet_gap_ms"] = gap
        return protocol.decode_value(resp.get("result")), info

    # ---- cross-process coalescing ----
    def _predict_coalesced(self, model: str, x, deadline_ms,
                           trace_id, priority_class
                           ) -> Tuple[Any, Dict[str, Any]]:
        """Merge concurrent compatible predicts into ONE wire request
        (leader/rider).  Compatibility is the batching contract: same
        model, priority class, deadline value, dtype, and trailing
        shape — rows concatenate on axis 0 exactly like the worker's
        own coalescer merges them, so the fleet answer stays
        bit-exact vs per-request sends.  Riders share the leader's
        outcome, including its error: a shed batch sheds every
        caller, same as the in-process coalescer."""
        import numpy as np
        key = (model, priority_class, deadline_ms,
               str(x.dtype), x.shape[1:])
        with self._co_lock:
            b = self._co_open.get(key)
            if (b is not None and not b.closed
                    and b.total + len(x) <= self.coalesce_rows):
                my_off = b.total
                b.rows.append(x)
                b.sizes.append(len(x))
                b.total += len(x)
                leader = False
            else:
                b = _Batch()
                b.rows.append(x)
                b.sizes.append(len(x))
                b.total = len(x)
                self._co_open[key] = b
                leader = True
        if not leader:
            # the leader's serve carries the deadline; the extra
            # margin only guards against a lost leader thread
            if not b.done.wait(self.call_timeout_s + 30.0):
                raise WorkerUnavailable(
                    "coalesced batch leader never completed",
                    model=model)
            if b.error is not None:
                raise b.error
            out = b.result[my_off:my_off + len(x)]
            info = dict(b.info or {})
            info["coalesced"] = b.total
            return out, info
        time.sleep(self.coalesce_ms / 1e3)  # the gather window
        with self._co_lock:
            if self._co_open.get(key) is b:
                del self._co_open[key]
            b.closed = True
            rows = list(b.rows)
        batch = rows[0] if len(rows) == 1 else np.concatenate(rows)
        try:
            out, info = self._serve_ex(
                {"op": "predict", "model": model, "inputs": batch},
                model, "predict", deadline_ms, trace_id,
                priority_class)
            b.result = np.asarray(out)
            b.info = info
        except BaseException as e:  # noqa: BLE001 — riders must see
            # the leader's failure, whatever its class
            b.error = e
            raise
        finally:
            b.done.set()
        info = dict(info)
        if len(rows) > 1:
            info["coalesced"] = b.total
        return b.result[:b.sizes[0]], info

    # ---- deploy / fan-out ----
    def deploy(self, model: str, params: Optional[Dict[str, Any]],
               builder: str, builder_args: Optional[dict] = None,
               warmup_shapes=None, version: Optional[int] = None,
               deploy_kwargs: Optional[dict] = None
               ) -> Dict[str, Any]:
        """Fleet deploy: persist the artifact once, then activate it
        on every worker one at a time (rolling, warm-before-swap per
        worker).  Returns the fan-out report ``{"version",
        "fanout_s", "activations": [{rank, compiles, warm_ms,
        error?}, ...]}``.  A worker that dies mid-fan-out is skipped —
        its restart replays the new version from the share."""
        # auto-versioning is seeded from the COMMITTED artifacts on
        # disk, not in-memory state alone: a restarted router must
        # never reuse a version number and overwrite an artifact
        # long-running workers still replay from (the spec rename is
        # the commit — committed artifacts are immutable)
        disk_floor = (max(artifact.versions(self.share_dir, model),
                          default=0) + 1 if version is None else 0)
        with self._lock:
            if version is None:
                version = max(self._next_version.get(model, 1),
                              disk_floor)
            self._next_version[model] = max(
                self._next_version.get(model, 1), version + 1)
        artifact.publish(
            self.share_dir, model, version, params,
            {"builder": builder, "args": builder_args or {},
             "warmup_shapes": (list(warmup_shapes)
                               if warmup_shapes is not None else None),
             "deploy_kwargs": deploy_kwargs or {}})
        # the version set updates BEFORE fan-out so a worker
        # restarting mid-deploy replays the NEW version (activation is
        # version-pinned and idempotent, double-activation is safe)
        with self._lock:
            self._active[model] = version
        t0 = time.perf_counter()
        activations: List[Dict[str, Any]] = []
        for h in list(self.handles):
            if not (h.routable or h.port is not None):
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            ta = time.perf_counter()
            try:
                resp = self._call(h, {"op": "activate", "model": model,
                                      "version": version})
                entry.update(resp["result"])
            except (ConnectionError, ServingError) as e:
                # dead worker: its replacement replays from the share.
                # A structured deploy failure is recorded, not raised
                # mid-fan-out — the report carries the verdict.
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_activate_failed", rank=h.rank,
                            model=model, version=version,
                            error=entry["error"])
            entry["t_start"] = round(ta - t0, 6)
            entry["t_end"] = round(time.perf_counter() - t0, 6)
            activations.append(entry)
        fanout_s = round(time.perf_counter() - t0, 6)
        with self._lock:
            self._fanouts[(model, version)] = fanout_s
        self.last_fanout = activations
        _slog.info("fleet_deploy_fanout", model=model, version=version,
                   fanout_s=fanout_s,
                   workers=[a["rank"] for a in activations])
        return {"version": version, "fanout_s": fanout_s,
                "activations": activations}

    def promote(self, model: str) -> Dict[str, Any]:
        """Fan out a canary promote to every routable worker —
        deploy's per-worker error discipline: one dead worker is
        recorded and skipped (its replacement replays the PROMOTED
        version set), never an aborted half-promoted fleet."""
        results = []
        promoted: Optional[int] = None
        for h in list(self.handles):
            if not h.routable:
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            try:
                resp = self._call(h, {"op": "promote", "model": model})
                entry.update(resp["result"])
                promoted = entry["version"]
                # _active updates at the FIRST success (deploy's
                # discipline): a worker restarting mid-promote must
                # replay the promoted version, not the one it died on
                with self._lock:
                    self._active[model] = promoted
            except (ConnectionError, ServingError) as e:
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_promote_failed", rank=h.rank,
                            model=model, error=entry["error"])
            results.append(entry)
        return {"version": promoted, "activations": results}

    def undeploy(self, model: str) -> Dict[str, Any]:
        """Fan out an undeploy to every routable worker and RETIRE the
        model's fleet-level series: the per-(model, version) fan-out
        gauge and the active-version map are dropped, so a density
        fleet cycling hundreds of models does not grow the router
        scrape (or its memory) one dead series per deploy forever.
        Committed artifacts stay on the share (undeploy retires the
        SERVING state, not the deploy history); per-worker error
        discipline matches deploy/promote — a dead worker's
        replacement simply never replays the retired model."""
        results = []
        for h in list(self.handles):
            if not h.routable:
                continue
            entry: Dict[str, Any] = {"rank": h.rank}
            try:
                resp = self._call(h, {"op": "undeploy",
                                      "model": model})
                entry.update(resp["result"])
            except (ConnectionError, ServingError) as e:
                entry["error"] = f"{type(e).__name__}: {e}"
                _slog.error("fleet_undeploy_failed", rank=h.rank,
                            model=model, error=entry["error"])
            results.append(entry)
        with self._lock:
            self._active.pop(model, None)
            self._next_version.pop(model, None)
            for key in [k for k in self._fanouts if k[0] == model]:
                self._fanouts.pop(key, None)
        _slog.info("fleet_undeploy", model=model,
                   workers=[r["rank"] for r in results])
        return {"model": model, "activations": results}

    def ping(self, rank: int) -> Dict[str, Any]:
        return self._call(self.handles[rank],
                          {"op": "ping"})["result"]

    # ---- elastic pool ----
    def pool_size(self) -> int:
        """Workers that count toward capacity: everything not
        deliberately retired and not past its restart budget."""
        return sum(1 for w in self.supervisor.workers
                   if w.state not in ("retired", "dead"))

    def load_signals(self) -> Dict[str, Any]:
        """The autoscaler's view of the fleet: router-side in-flight
        total (the timely number — worker piggybacks lag one reply),
        the served-latency EWMA, and the live pool size."""
        with self._lock:
            depth = sum(h.outstanding for h in self.handles)
            ewma = self._ewma_ms
        return {"queue_depth": depth, "ewma_ms": ewma,
                "active": self.pool_size()}

    def set_pool_size(self, n: int, *, drain_timeout_s: float = 30.0,
                      start_timeout_s: float = 120.0
                      ) -> Dict[str, Any]:
        """Resize the worker plane to ``n`` workers (the autoscaler's
        ``apply_scale``, also a first-class operator verb).

        Scale-UP revives retired slots first, then appends fresh
        ranks; either way the supervisor's ``on_worker_up`` replay
        warms the newcomer from the shared execstore BEFORE it turns
        routable — zero compiles, gated by the fleet drill — and this
        call blocks until the newcomer is routable (the autoscaler
        contract: apply_scale is synchronous).

        Scale-DOWN picks the highest-rank active workers, latches
        ``retiring`` (no new picks, revival probes disarmed), DRAINS
        the router-side in-flight count to zero, then retires the
        process through the supervisor — a deliberate exit, not an
        incident.  A drain that outlives ``drain_timeout_s`` retires
        anyway (the straggler's caller gets the retry-on-sibling
        path) and reports ``forced``."""
        if n < 1:
            raise ValueError(f"pool size must be >= 1, got {n}")
        report: Dict[str, Any] = {"target": n, "grew": [],
                                  "retired": [], "forced": []}
        while self.pool_size() < n:
            retired = [w for w in self.supervisor.workers
                       if w.state == "retired"]
            if retired:
                rank = retired[0].rank
                h = self.handles[rank]
                h.retiring = False
                h.drop_conns()
                self.supervisor.revive(rank)
            else:
                with self._lock:
                    rank = len(self.supervisor.workers)
                    # the handle EXISTS before the spawn: the monitor
                    # thread's on_worker_up replay dereferences it
                    self.handles.append(_Handle(rank))
                self.supervisor.add_worker()
            deadline = time.monotonic() + start_timeout_s
            h = self.handles[rank]
            while not h.routable:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"scale-up worker {rank} not routable within "
                        f"{start_timeout_s}s: "
                        f"{self.supervisor.states()}")
                if self.supervisor.worker(rank).state == "dead":
                    raise RuntimeError(
                        f"scale-up worker {rank} died during warm-up")
                time.sleep(0.02)
            report["grew"].append(rank)
            _slog.info("fleet_scale_up", rank=rank,
                       pool=self.pool_size())
        while self.pool_size() > n:
            active = [w for w in self.supervisor.workers
                      if w.state not in ("retired", "dead")]
            victim = max(active, key=lambda w: w.rank)
            h = self.handles[victim.rank]
            h.retiring = True
            h.routable = False
            deadline = time.monotonic() + drain_timeout_s
            while True:
                with self._lock:
                    drained = h.outstanding == 0
                if drained:
                    break
                if time.monotonic() > deadline:
                    report["forced"].append(victim.rank)
                    _slog.warning("fleet_scale_down_forced",
                                  rank=victim.rank,
                                  outstanding=h.outstanding)
                    break
                time.sleep(0.01)
            # cooperative shutdown first: the worker's serve loop has a
            # "shutdown" handler for exactly this, and a worker that
            # exits on its own skips the supervisor's terminate->kill
            # escalation (retire() marks it "retired" before the exit
            # lands, so the monitor never books it as an incident)
            try:
                self._call(h, {"op": "shutdown"})
            except (ConnectionError, ServingError):
                pass  # drain already emptied it; terminate() below wins
            h.drop_conns()
            h.port = None
            h.resident = frozenset()
            self.supervisor.retire(victim.rank)
            report["retired"].append(victim.rank)
            _slog.info("fleet_scale_down", rank=victim.rank,
                       pool=self.pool_size())
        return report

    # ---- observability ----
    def families(self) -> List[Family]:
        states = self.supervisor.states()
        with self._lock:
            retries = self._retries_total
            fanouts = dict(self._fanouts)
            affinity = dict(self._affinity)
            wire_bytes = dict(self._wire_bytes)
        fams = [
            Family("gauge", "zoo_fleet_workers",
                   "fleet workers by supervision state",
                   [({"state": s}, n) for s, n in sorted(states.items())]),
            Family("counter", "zoo_fleet_router_retries_total",
                   "requests retried on a sibling after a worker "
                   "death mid-request", [({}, retries)]),
            Family("counter", "zoo_fleet_affinity_total",
                   "residency-aware routing outcomes (hit: landed "
                   "on a worker holding the model; miss: resident "
                   "worker existed but load won; cold: nobody held "
                   "it)",
                   [({"outcome": o}, n)
                    for o, n in sorted(affinity.items())]),
            Family("counter", "zoo_fleet_wire_bytes_total",
                   "router<->worker frame bytes by direction and "
                   "payload encoding",
                   [({"direction": d, "encoding": e}, n)
                    for (d, e), n in sorted(wire_bytes.items())]),
        ]
        if fanouts:
            fams.append(Family(
                "gauge", "zoo_fleet_deploy_fanout_seconds",
                "wall seconds of the last activation fan-out per "
                "(model, version)",
                [({"model": m, "version": str(v)}, s)
                 for (m, v), s in sorted(fanouts.items())]))
        return fams

    def metrics_text(self) -> str:
        """The fleet scrape: every live worker's exposition merged
        through the pod aggregator (rank labels + counter fleet
        totals), the router's own families appended."""
        pairs = []
        for h in list(self.handles):
            if not h.routable:
                continue
            try:
                resp = self._call(h, {"op": "metrics"})
            except (ConnectionError, ServingError):
                continue  # a worker dying mid-scrape skips one rank
            pairs.append((h.rank,
                          parse_prometheus_text(resp["result"]["text"])))
        fams = _aggregate.merge_snapshots(pairs)
        fams.extend(self.families())
        if self.tracer is not None:
            # the router's own trace families (span/phase aggregates
            # plus tail exemplar links) join the pod exposition under
            # rank="router" — distinct from every worker's rank label
            # AND from the aggregator's rank-less counter pod totals
            fams.extend(_aggregate.rank_labeled(
                self.tracer.families(), "router"))
        return render_prometheus(fams)

    def states(self) -> Dict[str, int]:
        return self.supervisor.states()

    @property
    def retries_total(self) -> int:
        with self._lock:
            return self._retries_total

    @property
    def affinity_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._affinity)

    @property
    def wire_bytes(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._wire_bytes)

    def set_wire(self, wire: str) -> None:
        """Flip the fleet's wire mode ("binary" negotiates v2 per
        connection, "json" pins v1) and drop every pooled connection
        so the next exchange renegotiates — the drill's A/B lever."""
        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be binary|json, got {wire!r}")
        self.wire = wire
        for h in list(self.handles):
            h.drop_conns()


def fleet_autoscaler(router: FleetRouter, **kwargs: Any):
    """PR 6's :class:`~..autoscale.Autoscaler` pointed at the WORKER
    PLANE: queue depth = the router's in-flight total, latency = its
    served EWMA, and ``apply_scale`` resizes the worker pool through
    :meth:`FleetRouter.set_pool_size` — whole processes instead of
    in-process replicas, with the execstore replay making every
    scale-up warm.  Same hysteresis/cooldown/±1 discipline, same
    testable ``tick()``.  ``max_replicas`` defaults to the current
    pool size (growing past the initial fleet is an explicit
    decision, not a default)."""
    from ..autoscale import Autoscaler

    def apply_scale(n: int):
        router.set_pool_size(n)

    kwargs.setdefault("max_replicas", router.pool_size())
    kwargs.setdefault("initial_replicas", router.pool_size())
    kwargs.setdefault("name", "fleet")
    return Autoscaler(router.load_signals, apply_scale, **kwargs)
