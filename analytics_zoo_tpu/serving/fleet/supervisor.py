"""Fleet supervisor: the PR 10 pod machinery re-aimed at the serving
plane — N worker processes, each independently crash-restarted.

The training supervisor (``launcher._run_supervised``) reaps the WHOLE
pod on one death because training workers are welded together by
collectives.  Serving workers are deliberately NOT: each is a complete
single-process data plane, so the right failure unit is one worker —
a crash (or a heartbeat stale past the watchdog window) costs the
fleet one worker's capacity while the others keep serving, and the
replacement warms back from the share + execstore in milliseconds.

Per worker, per incident:

* the corpse's flight recorder is harvested into
  ``worker_postmortem.r{rank}.i{inc}.json`` (PR 12's
  ``flightrec.write_postmortem``, with the supervisor-side evidence —
  exit rc, heartbeat age at detection — merged in);
* within ``max_restarts`` (per worker), a fresh incarnation relaunches
  after exponential backoff, with ``ZOO_RESTART_COUNT`` bumped so its
  recorder/log identity is correct and one-shot fault hooks disarm;
* ``on_worker_up(rank, port, incarnation)`` fires once the new
  incarnation is listening — the router uses it to replay the current
  version set onto the blank worker BEFORE routing traffic at it;
* past the budget the worker is ``dead`` and stays dead — the fleet
  degrades rather than crash-looping (``zoo_fleet_workers{state}``
  makes the degradation visible).

The pool is ELASTIC (PR 16): ``add_worker``/``revive`` grow it (the
new worker warms from the shared execstore via the same
``on_worker_up`` replay, so scale-up is zero-compile), and
``retire`` is the deliberate scale-down terminal — marked BEFORE the
terminate so the monitor never mistakes a drained worker's exit for
a crash.  The router owns the drain discipline around these.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ... import envcontract
from ...observability import flightrec
from ...observability.log import get_logger

_slog = get_logger("zoo.serving.fleet.supervisor")

_MAX_BACKOFF_S = 30.0
_POLL_S = 0.1


class _WorkerProc:
    """Supervisor-side record of one worker slot."""

    def __init__(self, rank: int):
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0
        self.restarts = 0
        # live | restarting | dead | retired — ``retired`` is the
        # elastic-pool scale-down terminal: deliberate, drained, NOT
        # an incident (no postmortem, no restart budget spent); the
        # slot can be revived by a later scale-up
        self.state = "restarting"
        self.port: Optional[int] = None
        self.port_file = ""
        self.hb_path = ""
        self.restart_at = 0.0
        self.last_reason: Optional[str] = None


class FleetSupervisor:
    """Spawn + supervise the worker plane (module docstring).

    ``env`` entries overlay the inherited environment for every worker
    (the caller points ``ZOO_EXECSTORE_DIR`` at the share, pins
    ``XLA_FLAGS``/``JAX_PLATFORMS``, ...).  ``on_worker_up`` /
    ``on_worker_down`` run on the monitor thread — keep them quick or
    lock-light (the router's re-activation warm is the intended
    heavyweight case; incidents on other workers queue behind it)."""

    def __init__(self, n_workers: int, run_dir: str, share_dir: str, *,
                 fake: bool = False,
                 registry_json: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 max_restarts: int = 2,
                 restart_backoff: float = 0.5,
                 watchdog_sec: float = 0.0,
                 on_worker_up: Optional[Callable] = None,
                 on_worker_down: Optional[Callable] = None):
        self.run_dir = run_dir
        self.share_dir = share_dir
        self.fake = fake
        self.registry_json = registry_json
        self.extra_env = dict(env or {})
        self.max_restarts = max_restarts
        self.restart_backoff = restart_backoff
        self.watchdog_sec = watchdog_sec
        self.on_worker_up = on_worker_up
        self.on_worker_down = on_worker_down
        self.workers = [_WorkerProc(r) for r in range(n_workers)]
        self.postmortems: List[str] = []
        self._stopping = False
        self._lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        os.makedirs(run_dir, exist_ok=True)

    # ---- lifecycle ----
    def flight_dir(self) -> str:
        """Shared flight-recorder base: a pre-set outer
        ``ZOO_FLIGHTREC_DIR`` wins (drills harvest it themselves) —
        the launcher's convention."""
        return (envcontract.env_str(flightrec.ENV_DIR)
                or os.path.join(self.run_dir, "flightrec"))

    def start(self) -> None:
        for w in self.workers:
            self._spawn(w)
        self._monitor = threading.Thread(target=self._watch,
                                         name="fleet-supervisor",
                                         daemon=True)
        self._monitor.start()

    def _spawn(self, w: _WorkerProc) -> None:
        inc = w.incarnation
        w.port = None
        w.port_file = os.path.join(self.run_dir,
                                   f"worker{w.rank}.i{inc}.port")
        w.hb_path = os.path.join(self.run_dir,
                                 f"hb_w{w.rank}.i{inc}")
        err_path = os.path.join(self.run_dir,
                                f"stderr_w{w.rank}.i{inc}.log")
        # a second supervisor lifetime over the same run_dir reuses
        # these paths: a STALE port file must not read as readiness
        # (it names a dead socket) and a stale heartbeat mtime must
        # not trip the watchdog before the fresh worker's first beat
        for stale in (w.port_file, w.hb_path):
            try:
                os.unlink(stale)
            except OSError:
                pass
        env = dict(os.environ)
        env.update(self.extra_env)
        env["ZOO_TPU_PROCESS_ID"] = str(w.rank)
        env["ZOO_RESTART_COUNT"] = str(inc)
        env["ZOO_HEARTBEAT_FILE"] = w.hb_path
        env[flightrec.ENV_DIR] = self.flight_dir()
        # a worker is not a training pod member: the trainer resume /
        # fault contract must not leak in from an outer drill
        env.pop("ZOO_RESUME", None)
        cmd = [sys.executable, "-m",
               "analytics_zoo_tpu.serving.fleet.worker",
               "--share", self.share_dir, "--port-file", w.port_file]
        if self.fake:
            cmd.append("--fake")
        if self.registry_json:
            cmd += ["--registry-json", self.registry_json]
        with open(err_path, "wb") as errf:
            w.proc = subprocess.Popen(cmd, env=env, stderr=errf)
        w.state = "restarting"  # live once the port file lands
        _slog.info("fleet_worker_spawned", rank=w.rank,
                   incarnation=inc, pid=w.proc.pid)

    # ---- monitoring ----
    def _watch(self) -> None:
        """The supervision poll loop: death detection + postmortem,
        bounded backoff restart, readiness promotion, heartbeat
        watchdog."""
        while not self._stopping:
            now = time.monotonic()
            for w in list(self.workers):
                if w.state in ("dead", "retired"):
                    continue
                if w.proc is not None:
                    rc = w.proc.poll()
                    if rc is not None and not self._stopping:
                        self._incident(w, rc)
                        continue
                if w.proc is None:
                    if now >= w.restart_at:
                        w.incarnation += 1
                        self._spawn(w)
                    continue
                if w.state == "restarting":
                    port = self._read_port(w)
                    if port is not None and now >= w.restart_at:
                        self._promote_live(w, port)
                elif (self.watchdog_sec and w.state == "live"):
                    age = self._hb_age(w)
                    if age is not None and age > self.watchdog_sec:
                        _slog.error("fleet_watchdog_kill", rank=w.rank,
                                    heartbeat_age_s=round(age, 3),
                                    watchdog_sec=self.watchdog_sec)
                        w.last_reason = "watchdog"
                        try:
                            w.proc.send_signal(signal.SIGKILL)
                        except OSError:
                            pass
            time.sleep(_POLL_S)

    def _read_port(self, w: _WorkerProc) -> Optional[int]:
        try:
            with open(w.port_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _hb_age(self, w: _WorkerProc) -> Optional[float]:
        try:
            return time.time() - os.path.getmtime(w.hb_path)
        except OSError:
            return None  # no beat yet: startup is covered by exits

    def _promote_live(self, w: _WorkerProc, port: int) -> None:
        w.port = port
        cb = self.on_worker_up
        if cb is not None:
            try:
                cb(w.rank, port, w.incarnation)
            except Exception as e:  # noqa: BLE001 — a failed replay
                # leaves the worker out of rotation; the next incident
                # or deploy retries it.  Never kill the monitor.
                _slog.error("fleet_worker_up_hook_failed", rank=w.rank,
                            error=f"{type(e).__name__}: {e}")
                w.restart_at = time.monotonic() + 0.5  # bounded retry
                return
        w.state = "live"
        _slog.info("fleet_worker_live", rank=w.rank, port=port,
                   incarnation=w.incarnation)

    def _incident(self, w: _WorkerProc, rc: int) -> None:
        """One worker death: evidence first, then the restart
        decision.  Heartbeat age is sampled at detection (the
        postmortem must reflect what the watchdog saw, not what the
        reap left behind)."""
        if w.state == "retired":
            # a deliberate retire whose exit the poll caught before
            # the state check: not an incident, no postmortem
            return
        reason = w.last_reason or "exit"
        w.last_reason = None
        age = self._hb_age(w)
        _slog.error("fleet_worker_down", rank=w.rank, rc=rc,
                    reason=reason, incarnation=w.incarnation,
                    heartbeat_age_s=(round(age, 3)
                                     if age is not None else None))
        cb = self.on_worker_down
        if cb is not None:
            try:
                cb(w.rank)
            except Exception:  # noqa: BLE001
                pass
        pm_path = os.path.join(
            self.run_dir,
            f"worker_postmortem.r{w.rank}.i{w.incarnation}.json")
        try:
            flightrec.write_postmortem(
                self.flight_dir(), pm_path, reason=reason,
                failed_rank=w.rank, incarnation=w.incarnation,
                supervisor={w.rank: {
                    "rc": rc,
                    "heartbeat_age_s": (round(age, 3)
                                        if age is not None else None)}})
            self.postmortems.append(pm_path)
        except Exception as e:  # noqa: BLE001 — a postmortem failure
            # must never eat the restart itself
            _slog.error("fleet_postmortem_failed", rank=w.rank,
                        error=f"{type(e).__name__}: {e}")
        w.proc = None
        w.port = None
        if w.restarts >= self.max_restarts:
            w.state = "dead"
            _slog.error("fleet_worker_dead", rank=w.rank,
                        restarts=w.restarts,
                        max_restarts=self.max_restarts)
            return
        w.restarts += 1
        backoff = min(self.restart_backoff * (2 ** (w.restarts - 1)),
                      _MAX_BACKOFF_S)
        w.state = "restarting"
        w.restart_at = time.monotonic() + backoff
        _slog.warning("fleet_worker_restarting", rank=w.rank,
                      restart=w.restarts, backoff_s=round(backoff, 3))

    # ---- elastic pool ----
    def add_worker(self) -> int:
        """Scale-up: append a fresh worker slot and spawn it (the
        monitor promotes it live once its port file lands, firing
        ``on_worker_up`` — the execstore replay warm happens there,
        so a scale-up worker joins at zero compiles).  Returns the
        new rank."""
        with self._lock:
            w = _WorkerProc(len(self.workers))
            self.workers.append(w)
        self._spawn(w)
        _slog.info("fleet_worker_added", rank=w.rank)
        return w.rank

    def revive(self, rank: int) -> None:
        """Scale-up into a previously retired slot: a fresh
        incarnation with a fresh restart budget (retirement was
        deliberate, not a crash record to hold against it)."""
        w = self.workers[rank]
        if w.state != "retired":
            raise ValueError(f"worker {rank} is {w.state}, not retired")
        w.restarts = 0
        w.incarnation += 1
        w.restart_at = 0.0
        self._spawn(w)
        _slog.info("fleet_worker_revived_slot", rank=rank)

    def retire(self, rank: int, grace_s: float = 5.0) -> None:
        """Scale-down terminal for one DRAINED worker: mark retired
        FIRST (so the monitor treats the exit as deliberate — no
        postmortem, no restart), then terminate → grace → kill →
        reap.  The caller owns the drain: no new work routed and
        in-flight requests completed before calling this."""
        w = self.workers[rank]
        w.state = "retired"
        w.port = None
        p, w.proc = w.proc, None
        if p is not None and p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        _slog.info("fleet_worker_retired", rank=rank)

    # ---- introspection ----
    def states(self) -> Dict[str, int]:
        out = {"live": 0, "restarting": 0, "dead": 0, "retired": 0}
        for w in self.workers:
            out[w.state] = out.get(w.state, 0) + 1
        return out

    def live_workers(self) -> List[_WorkerProc]:
        return [w for w in self.workers
                if w.state == "live" and w.port is not None]

    def worker(self, rank: int) -> _WorkerProc:
        return self.workers[rank]

    def kill(self, rank: int, sig: int = signal.SIGKILL) -> None:
        """Drill hook: SIGKILL one worker (the supervisor detects and
        restarts it exactly as it would a real crash)."""
        w = self.workers[rank]
        if w.proc is not None and w.proc.poll() is None:
            w.proc.send_signal(sig)

    # ---- shutdown ----
    def stop(self, grace_s: float = 5.0) -> None:
        """Tear the fleet down: terminate → grace → kill, monitor
        joined.  Idempotent."""
        self._stopping = True
        if self._monitor is not None:
            self._monitor.join(timeout=10)
            self._monitor = None
        procs = [w.proc for w in self.workers if w.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + grace_s
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1,
                                       deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    pass
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
