"""Fleet serving: multi-process serving pods behind a router with a
distributed control plane.

Everything below ``serving/`` so far runs in ONE process; a fleet is
N supervised worker processes — each the full single-process data
plane (``ModelRegistry`` + bucketed executables + coalescer +
admission) behind a localhost frame protocol — and a thin router that
speaks the same serving envelope outward, spreads load
least-outstanding-work, retries a worker death mid-request once on a
sibling, and deploys by persisting ONE artifact + fanning out
warm-before-swap activations that hit the shared execstore (zero
compiles on every worker after the first).  See docs/serving.md
§"Fleet serving".

Fleet v2 (PR 16): the data plane rides a NEGOTIATED binary wire
(ndarrays out-of-band, zero-copy decode) with per-direction byte
accounting; routing is residency-aware (workers piggyback their pager
residency, the scheduler weights least-outstanding-work by it — N
pagers become one fleet cache); and the pool is elastic
(:func:`fleet_autoscaler` drives ``FleetRouter.set_pool_size``:
zero-compile warm scale-up via execstore replay, drain-before-retire
scale-down).  See docs/serving.md §"Fleet v2".

* :mod:`.protocol` — length-prefixed CRC-framed envelope codec (JSON
  + binary payloads);
* :mod:`.artifact` — the committed on-share deploy artifact;
* :mod:`.builders` — reference artifact builders (mlp, stub);
* :mod:`.worker` — the worker process (``python -m ...fleet.worker``);
* :mod:`.supervisor` — per-worker crash-restart/watchdog/postmortem;
* :mod:`.router` — scheduling, fan-out, fleet metrics.
"""

from . import artifact, builders, protocol
from .router import FleetRouter, WorkerUnavailable, fleet_autoscaler
from .supervisor import FleetSupervisor

__all__ = ["FleetRouter", "FleetSupervisor", "WorkerUnavailable",
           "fleet_autoscaler", "artifact", "builders", "protocol"]
