"""Fleet wire protocol: length-prefixed JSON frames over a localhost
socket, carrying the registry's serving envelope across processes.

One frame = an 8-byte little-endian header (``payload length`` +
``CRC32`` of the payload, the flight recorder's framing discipline
applied to a stream) followed by the UTF-8 JSON payload.  The whole
frame is sent with ONE ``sendall`` so a worker SIGKILLed mid-reply
leaves the reader a cleanly detectable torn frame, never a silently
truncated JSON document parsed as something shorter.

The payload is the existing control-plane envelope verbatim:

* requests — ``{"op", "id", ...op fields}`` where the op fields are
  exactly the ``predict_ex``/``generate_ex`` keyword surface
  (``model``, ``deadline_ms``, ``trace_id``, ``priority_class``, and
  for generate the sampling envelope ``temperature``/``top_k``/
  ``top_p``/``seed`` — plain json scalars, so cross-process
  determinism reduces to the engine's process-free fold_in RNG: the
  same request through any worker replays the single-process
  registry's tokens bit-exactly, re-gated by
  tests/test_fleet.py::test_cross_process_generate_determinism) plus
  the fleet control ops (``activate``, ``promote``, ``metrics``,
  ``ping``, ``shutdown``);
* responses — ``{"id", "ok": true, "result", "info"}`` on success, or
  ``{"id", "ok": false, "error": <ServingError.to_dict()>}`` on
  failure.  :func:`decode_error` reconstructs the CONCRETE serving
  exception class on the client side — an ``Overloaded(evicted=True)``
  raised in a worker is an ``Overloaded`` with ``evicted=True`` in the
  router's caller, details, http_status and all.

Arrays cross the wire as ``{"__nd__": {dtype, shape, b64}}`` (raw
``tobytes`` base64) — bit-exact round-trip by construction, which the
fleet drill's bit-identical gate leans on.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import zlib
from typing import Any, Dict, Optional

from .. import errors as _errors

_HEADER = struct.Struct("<II")  # payload length, CRC32(payload)

#: hard frame bound: a fleet request is a batch of rows, not a dataset
#: — a corrupt length prefix must not allocate gigabytes before the
#: CRC gets a chance to convict it
MAX_FRAME_BYTES = 256 << 20


class FrameError(ConnectionError):
    """A torn, short, corrupt, or oversized frame — the stream is no
    longer trustworthy and the connection must be dropped (the router
    treats it exactly like a worker death: retry on a sibling)."""


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    """Serialize + send one frame with a single ``sendall``."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame of {len(payload)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES} byte bound")
    sock.sendall(_HEADER.pack(len(payload),
                              zlib.crc32(payload) & 0xffffffff)
                 + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF BEFORE the first
    byte (a peer closing between frames is a normal hangup), raises
    :class:`FrameError` on EOF mid-buffer (a torn frame)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise FrameError(f"short read: {got}/{n} bytes then EOF")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Read one frame.  Returns None on a clean EOF at a frame
    boundary; raises :class:`FrameError` on a torn frame (EOF inside
    the header or payload), a CRC mismatch, an oversized length, or an
    undecodable payload."""
    head = _recv_exact(sock, _HEADER.size)
    if head is None:
        return None
    length, crc = _HEADER.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds the "
                         f"{MAX_FRAME_BYTES} byte bound")
    payload = _recv_exact(sock, length)
    if payload is None:
        raise FrameError(f"EOF between header and {length}-byte payload")
    if zlib.crc32(payload) & 0xffffffff != crc:
        raise FrameError("frame CRC mismatch")
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame payload: {e}") from e


# -------------------------------------------------------------- arrays
def encode_array(a) -> Dict[str, Any]:
    """One ndarray as a JSON-safe dict (raw bytes, bit-exact)."""
    import numpy as np
    a = np.ascontiguousarray(a)
    return {"__nd__": {"dtype": str(a.dtype), "shape": list(a.shape),
                       "b64": base64.b64encode(a.tobytes()).decode()}}


def decode_array(obj: Dict[str, Any]):
    import numpy as np
    nd = obj["__nd__"]
    return np.frombuffer(
        base64.b64decode(nd["b64"]),
        dtype=np.dtype(nd["dtype"])).reshape(nd["shape"]).copy()


def encode_value(v: Any) -> Any:
    """Arrays (and lists/tuples/dicts containing them) to wire form;
    everything JSON-native passes through."""
    import numpy as np
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: encode_value(x) for k, x in v.items()}
    if isinstance(v, np.ndarray) or (
            hasattr(v, "__array__")
            and not isinstance(v, (str, bytes, bool, int, float))):
        return encode_array(np.asarray(v))
    return v


def decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__nd__" in v:
            return decode_array(v)
        return {k: decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


# -------------------------------------------------------------- errors
_ERROR_CLASSES = {
    "ModelNotFound": _errors.ModelNotFound,
    "Overloaded": _errors.Overloaded,
    "DeadlineExceeded": _errors.DeadlineExceeded,
    "DeployError": _errors.DeployError,
    "ServingError": _errors.ServingError,
    # a worker's cold-start SLO miss must reach the client as the
    # concrete 503 — and, being a structured serving error, it is
    # NEVER retried on a sibling (the router's rule), so one slow
    # fault cannot make every worker fault the same model
    "ColdStartTimeout": _errors.ColdStartTimeout,
}


def _json_safe(v: Any) -> Any:
    """Detail values must never make an error envelope unsendable: a
    non-JSON value degrades to its repr (the caller still gets the
    concrete class and message) instead of a TypeError that would
    kill the connection and read as a worker death."""
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def encode_error(exc: BaseException) -> Dict[str, Any]:
    """An exception as the wire error envelope.  ServingErrors carry
    their full structured ``to_dict()`` (code + message + details);
    anything else degrades to a generic envelope with the type name —
    same contract as :func:`..errors.error_response`."""
    if isinstance(exc, _errors.ServingError):
        return {k: _json_safe(v) for k, v in exc.to_dict().items()}
    return {"error": type(exc).__name__, "message": str(exc)}


def decode_error(payload: Dict[str, Any]) -> BaseException:
    """The wire error envelope back into a raisable exception: known
    serving codes reconstruct the CONCRETE class with details intact
    (``evicted``, ``shed``, ... survive the hop); unknown codes become
    a ``ServingError`` so the caller still gets the structured
    surface, never a bare string."""
    payload = dict(payload)
    code = payload.pop("error", "ServingError")
    message = payload.pop("message", code)
    cls = _ERROR_CLASSES.get(code)
    if cls is None:
        err = _errors.ServingError(message, **payload)
        err.details["error"] = code  # preserve the original code
        return err
    return cls(message, **payload)
