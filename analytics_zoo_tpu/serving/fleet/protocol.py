"""Fleet wire protocol: length-prefixed JSON frames over a localhost
socket, carrying the registry's serving envelope across processes.

One frame = an 8-byte little-endian header (``payload length`` +
``CRC32`` of the payload, the flight recorder's framing discipline
applied to a stream) followed by the UTF-8 JSON payload.  The whole
frame is sent with ONE ``sendall`` so a worker SIGKILLed mid-reply
leaves the reader a cleanly detectable torn frame, never a silently
truncated JSON document parsed as something shorter.

The payload is the existing control-plane envelope verbatim:

* requests — ``{"op", "id", ...op fields}`` where the op fields are
  exactly the ``predict_ex``/``generate_ex`` keyword surface
  (``model``, ``deadline_ms``, ``trace_id``, ``priority_class``, and
  for generate the sampling envelope ``temperature``/``top_k``/
  ``top_p``/``seed`` — plain json scalars, so cross-process
  determinism reduces to the engine's process-free fold_in RNG: the
  same request through any worker replays the single-process
  registry's tokens bit-exactly, re-gated by
  tests/test_fleet.py::test_cross_process_generate_determinism) plus
  the fleet control ops (``activate``, ``promote``, ``metrics``,
  ``ping``, ``shutdown``);
* responses — ``{"id", "ok": true, "result", "info"}`` on success, or
  ``{"id", "ok": false, "error": <ServingError.to_dict()>}`` on
  failure.  Every reply additionally piggybacks worker state: ``load``
  (serve-op in-flight count + throttled residency snapshot, feeding
  the router's affinity view for free) and — only when the request
  carried a ``trace_id`` — ``trace``, a compact summary of the
  worker-side span (closed phases, coverage, labels, wall/monotonic
  start anchors, rank/incarnation) that the router nests under its
  own ``worker_call`` phase; ``observability/tracefleet.py`` owns the
  summary shape and the stitching.  :func:`decode_error` reconstructs the CONCRETE serving
  exception class on the client side — an ``Overloaded(evicted=True)``
  raised in a worker is an ``Overloaded`` with ``evicted=True`` in the
  router's caller, details, http_status and all.

Two payload encodings share the framing.  The original JSON payload
carries arrays as ``{"__nd__": {dtype, shape, b64}}`` (raw ``tobytes``
base64) — bit-exact round-trip by construction, which the fleet
drill's bit-identical gate leans on, but +33% bytes and an
encode/decode copy per array per hop.  The v2 BINARY payload
(:func:`encode_binary`/:func:`decode_binary`) carries ndarrays
out-of-band: a magic prefix, a compact JSON header holding the
envelope with each array replaced by a slot reference plus a
``[dtype, shape, offset, nbytes]`` table, then the raw buffer bytes —
still one ``sendall``, still one CRC over the whole payload, decoded
with ``np.frombuffer`` into ZERO-COPY views over the received buffer.
The first payload byte discriminates (``0xff`` can never begin a JSON
text), so :func:`recv_envelope` reads either encoding without
negotiation; which encoding a peer may be SENT is negotiated once per
connection via the ``hello`` op (old workers answer ``unknown op`` and
the router falls back to JSON for that connection).

The frame-size bound defaults to 256 MiB and is configurable via
``ZOO_FLEET_MAX_FRAME`` (bytes).
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ... import envcontract
from .. import errors as _errors

_HEADER = struct.Struct("<II")  # payload length, CRC32(payload)

#: hard frame bound: a fleet request is a batch of rows, not a dataset
#: — a corrupt length prefix must not allocate gigabytes before the
#: CRC gets a chance to convict it
MAX_FRAME_BYTES = 256 << 20

#: wire versions a connection can negotiate (``hello`` op)
WIRE_JSON = 1
WIRE_BINARY = 2

#: binary payloads open with a byte no JSON text can start with
BIN_MAGIC = b"\xffZB2\x00"
_BIN_HLEN = struct.Struct("<I")
_BIN_ALIGN = 8  # array buffers land 8-byte aligned for frombuffer


def max_frame_bytes() -> int:
    """The effective frame bound: ``ZOO_FLEET_MAX_FRAME`` (bytes) when
    set and parseable, else :data:`MAX_FRAME_BYTES`.  Read per call so
    a worker env override applies without plumbing."""
    v = envcontract.env_int("ZOO_FLEET_MAX_FRAME")
    return v if v > 0 else MAX_FRAME_BYTES


class FrameError(ConnectionError):
    """A torn, short, corrupt, or oversized frame — the stream is no
    longer trustworthy and the connection must be dropped (the router
    treats it exactly like a worker death: retry on a sibling).
    ``attempted_bytes`` is set on the OVERSIZE-send flavor, where no
    bytes hit the socket: the worker degrades that one to a structured
    error reply carrying the size instead of dropping the peer."""

    def __init__(self, message: str,
                 attempted_bytes: Optional[int] = None):
        super().__init__(message)
        self.attempted_bytes = attempted_bytes


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    """Serialize + send one JSON frame with a single ``sendall``."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    cap = max_frame_bytes()
    if len(payload) > cap:
        raise FrameError(f"frame of {len(payload)} bytes exceeds the "
                         f"{cap} byte bound",
                         attempted_bytes=len(payload))
    sock.sendall(_HEADER.pack(len(payload),
                              zlib.crc32(payload) & 0xffffffff)
                 + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF BEFORE the first
    byte (a peer closing between frames is a normal hangup), raises
    :class:`FrameError` on EOF mid-buffer (a torn frame)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise FrameError(f"short read: {got}/{n} bytes then EOF")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_payload(sock: socket.socket) -> Optional[bytes]:
    """One frame's CRC-verified payload bytes (either encoding), or
    None on a clean EOF at a frame boundary."""
    head = _recv_exact(sock, _HEADER.size)
    if head is None:
        return None
    length, crc = _HEADER.unpack(head)
    cap = max_frame_bytes()
    if length > cap:
        raise FrameError(f"frame length {length} exceeds the "
                         f"{cap} byte bound")
    payload = _recv_exact(sock, length)
    if payload is None:
        raise FrameError(f"EOF between header and {length}-byte payload")
    if zlib.crc32(payload) & 0xffffffff != crc:
        raise FrameError("frame CRC mismatch")
    return payload


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Read one JSON frame.  Returns None on a clean EOF at a frame
    boundary; raises :class:`FrameError` on a torn frame (EOF inside
    the header or payload), a CRC mismatch, an oversized length, or an
    undecodable payload."""
    payload = _recv_payload(sock)
    if payload is None:
        return None
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame payload: {e}") from e


# -------------------------------------------------------------- arrays
def encode_array(a) -> Dict[str, Any]:
    """One ndarray as a JSON-safe dict (raw bytes, bit-exact)."""
    import numpy as np
    a = np.ascontiguousarray(a)
    return {"__nd__": {"dtype": str(a.dtype), "shape": list(a.shape),
                       "b64": base64.b64encode(a.tobytes()).decode()}}


def decode_array(obj: Dict[str, Any]):
    import numpy as np
    nd = obj["__nd__"]
    return np.frombuffer(
        base64.b64decode(nd["b64"]),
        dtype=np.dtype(nd["dtype"])).reshape(nd["shape"]).copy()


def encode_value(v: Any) -> Any:
    """Arrays (and lists/tuples/dicts containing them) to wire form;
    everything JSON-native passes through."""
    import numpy as np
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: encode_value(x) for k, x in v.items()}
    if isinstance(v, np.ndarray) or (
            hasattr(v, "__array__")
            and not isinstance(v, (str, bytes, bool, int, float))):
        return encode_array(np.asarray(v))
    return v


def decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__nd__" in v:
            return decode_array(v)
        return {k: decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


# ------------------------------------------------------- binary frames
def _binary_parts(obj: Dict[str, Any]
                  ) -> Tuple[List[Any], int, int]:
    """The v2 payload as a list of buffers ready for one join+sendall:
    ``[magic, header_len, header_json, pad?, buf0, pad?, buf1, ...]``.
    Arrays are hoisted out of the envelope into slot references so the
    header stays compact JSON; buffers follow raw, 8-byte aligned,
    offsets relative to the first buffer region.  Returns
    ``(parts, total_len, crc32)`` — the CRC is accumulated over the
    parts so the payload is never materialized twice on the encode
    side (the decode side is the zero-copy half)."""
    import numpy as np
    arrays: List[Any] = []

    def _enc(v: Any) -> Any:
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, (list, tuple)):
            return [_enc(x) for x in v]
        if isinstance(v, dict):
            return {k: _enc(x) for k, x in v.items()}
        if isinstance(v, np.ndarray) or (
                hasattr(v, "__array__")
                and not isinstance(v, (str, bytes, bool, int, float))):
            a = np.ascontiguousarray(np.asarray(v))
            arrays.append(a)
            return {"__ndslot__": len(arrays) - 1}
        return v

    env = _enc(obj)
    nd = []
    off = 0
    for a in arrays:
        off += (-off) % _BIN_ALIGN
        nd.append([str(a.dtype), list(a.shape), off, a.nbytes])
        off += a.nbytes
    header = json.dumps({"env": env, "nd": nd},
                        separators=(",", ":")).encode("utf-8")
    parts: List[Any] = [BIN_MAGIC, _BIN_HLEN.pack(len(header)), header]
    pos = 0
    for a in arrays:
        pad = (-pos) % _BIN_ALIGN
        if pad:
            parts.append(b"\x00" * pad)
        parts.append(a.data if a.nbytes else b"")
        pos += pad + a.nbytes
    total = len(BIN_MAGIC) + _BIN_HLEN.size + len(header) + pos
    crc = 0
    for p in parts:
        crc = zlib.crc32(p, crc)
    return parts, total, crc & 0xffffffff


def encode_binary(obj: Dict[str, Any]) -> bytes:
    """One envelope as the v2 binary payload (a zoolint hot entry:
    every negotiated predict/generate request and reply encodes
    through here)."""
    parts, _, _ = _binary_parts(obj)
    return b"".join(parts)


def decode_binary(payload: bytes) -> Dict[str, Any]:
    """The v2 binary payload back into an envelope (a zoolint hot
    entry).  Array values come back as read-only ``np.frombuffer``
    views over ``payload`` — ZERO copies; the views keep the buffer
    alive, and every consumer downstream (coalescer staging, jax
    device put) copies-on-use anyway."""
    import numpy as np
    try:
        hlen, = _BIN_HLEN.unpack_from(payload, len(BIN_MAGIC))
        base = len(BIN_MAGIC) + _BIN_HLEN.size
        header = json.loads(payload[base:base + hlen].decode("utf-8"))
        body = base + hlen
        mv = memoryview(payload)
        views = []
        for dtype, shape, off, nbytes in header["nd"]:
            start = body + off
            views.append(np.frombuffer(
                mv[start:start + nbytes],
                dtype=np.dtype(dtype)).reshape(shape))
    except (struct.error, KeyError, IndexError, ValueError,
            TypeError, UnicodeDecodeError) as e:
        raise FrameError(f"undecodable binary payload: "
                         f"{type(e).__name__}: {e}") from e

    def _dec(v: Any) -> Any:
        if isinstance(v, dict):
            if "__ndslot__" in v:
                return views[v["__ndslot__"]]
            return {k: _dec(x) for k, x in v.items()}
        if isinstance(v, list):
            return [_dec(x) for x in v]
        return v

    return _dec(header["env"])


# ------------------------------------------------------ envelope wire
def send_envelope(sock: socket.socket, obj: Dict[str, Any],
                  binary: bool = False) -> int:
    """Send one envelope in the requested encoding, ONE ``sendall``
    either way; returns the frame's total wire bytes (the router's
    ``zoo_fleet_wire_bytes_total`` feed).  The oversize check fires
    BEFORE any bytes hit the socket — the connection stays usable and
    the caller can degrade to a structured error reply."""
    if not binary:
        payload = json.dumps(encode_value(obj),
                             separators=(",", ":")).encode("utf-8")
        cap = max_frame_bytes()
        if len(payload) > cap:
            raise FrameError(
                f"frame of {len(payload)} bytes exceeds the {cap} "
                f"byte bound", attempted_bytes=len(payload))
        sock.sendall(_HEADER.pack(len(payload),
                                  zlib.crc32(payload) & 0xffffffff)
                     + payload)
        return _HEADER.size + len(payload)
    parts, total, crc = _binary_parts(obj)
    cap = max_frame_bytes()
    if total > cap:
        raise FrameError(f"frame of {total} bytes exceeds the {cap} "
                         f"byte bound", attempted_bytes=total)
    sock.sendall(b"".join([_HEADER.pack(total, crc)] + parts))
    return _HEADER.size + total


def recv_envelope(sock: socket.socket
                  ) -> Optional[Tuple[Dict[str, Any], int, str]]:
    """Read one envelope of EITHER encoding (the first payload byte
    discriminates): ``(envelope, wire_bytes, "binary"|"json")``, or
    None on a clean EOF at a frame boundary.  JSON payloads get
    ``decode_value`` applied (``__nd__`` arrays materialize); binary
    payloads decode to zero-copy views — either way the caller sees
    plain envelopes with real ndarrays."""
    payload = _recv_payload(sock)
    if payload is None:
        return None
    nbytes = _HEADER.size + len(payload)
    if payload.startswith(BIN_MAGIC):
        return decode_binary(payload), nbytes, "binary"
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame payload: {e}") from e
    return decode_value(obj), nbytes, "json"


# -------------------------------------------------------------- errors
_ERROR_CLASSES = {
    "ModelNotFound": _errors.ModelNotFound,
    "Overloaded": _errors.Overloaded,
    "DeadlineExceeded": _errors.DeadlineExceeded,
    "DeployError": _errors.DeployError,
    "ServingError": _errors.ServingError,
    # a worker's cold-start SLO miss must reach the client as the
    # concrete 503 — and, being a structured serving error, it is
    # NEVER retried on a sibling (the router's rule), so one slow
    # fault cannot make every worker fault the same model
    "ColdStartTimeout": _errors.ColdStartTimeout,
    # the router's own 503: without this entry a worker-raised (or
    # proxied) WorkerUnavailable decoded on the client came back as a
    # bare ServingError with http_status 500 — the isinstance retry
    # rules and status mapping both lost the concrete class
    "WorkerUnavailable": _errors.WorkerUnavailable,
}


def _json_safe(v: Any) -> Any:
    """Detail values must never make an error envelope unsendable: a
    non-JSON value degrades to its repr (the caller still gets the
    concrete class and message) instead of a TypeError that would
    kill the connection and read as a worker death."""
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def encode_error(exc: BaseException) -> Dict[str, Any]:
    """An exception as the wire error envelope.  ServingErrors carry
    their full structured ``to_dict()`` (code + message + details);
    anything else degrades to a generic envelope with the type name —
    same contract as :func:`..errors.error_response`."""
    if isinstance(exc, _errors.ServingError):
        return {k: _json_safe(v) for k, v in exc.to_dict().items()}
    return {"error": type(exc).__name__, "message": str(exc)}


def decode_error(payload: Dict[str, Any]) -> BaseException:
    """The wire error envelope back into a raisable exception: known
    serving codes reconstruct the CONCRETE class with details intact
    (``evicted``, ``shed``, ... survive the hop); unknown codes become
    a ``ServingError`` so the caller still gets the structured
    surface, never a bare string."""
    payload = dict(payload)
    code = payload.pop("error", "ServingError")
    message = payload.pop("message", code)
    cls = _ERROR_CLASSES.get(code)
    if cls is None:
        err = _errors.ServingError(message, **payload)
        err.details["error"] = code  # preserve the original code
        return err
    return cls(message, **payload)
