"""The fleet deploy artifact: one model version persisted ONCE on the
shared directory, activated by every worker.

``deploy()`` on the router must not ship live weights over N sockets,
and a restarted worker must be able to rebuild the version the fleet
is serving with nobody pushing bytes at it.  So a deploy lands a
self-describing artifact under the share::

    <share>/deploys/<model>/v<version>/
        weights.npz   # flattened param tree, raw float bytes
        spec.json     # builder + args + registry deploy kwargs  (THE
                      # COMMIT POINT: written last, atomic rename)

``spec.json`` landing is the commit — a worker listing versions never
sees a half-written artifact (the ``weights.npz`` of an uncommitted
deploy is invisible until its spec renames in; same discipline as the
checkpoint commit manifests and the execstore entries).

The spec's ``builder`` is a dotted ``module:callable`` path resolved
IN THE WORKER; called as ``builder(args, params)`` it returns the
``ModelRegistry.deploy`` keyword dict for this version (usually
``{"jax_fn": fn, "params": params}``, or ``{"model": handle}`` for a
duck-typed plane — the fake worker mode used by the no-jax tier-1
tests).  Reference builders live in :mod:`.builders`.

The artifact intentionally carries NO executables: those live in the
execstore keyed by content fingerprint — the artifact is the recipe,
the store is the compiled result, and a worker that finds the store
warm activates in milliseconds with zero compiles.
"""

from __future__ import annotations

import importlib
import json
import os
import re
from typing import Any, Callable, Dict, Optional, Tuple

from ...observability.flightrec import atomic_write

_SPEC = "spec.json"
_WEIGHTS = "weights.npz"
_VDIR_RE = re.compile(r"^v(\d+)$")
_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def deploys_root(share_dir: str) -> str:
    return os.path.join(share_dir, "deploys")


def _version_dir(share_dir: str, model: str, version: int) -> str:
    if not _NAME_RE.match(model):
        # model names become path components; reject traversal early
        raise ValueError(f"invalid model name {model!r}")
    return os.path.join(deploys_root(share_dir), model, f"v{version}")


def publish(share_dir: str, model: str, version: int,
            params: Optional[Dict[str, Any]], spec: Dict[str, Any]
            ) -> str:
    """Persist one version's artifact; returns its directory.  The
    spec lands LAST via atomic rename — its presence IS the commit.
    ``params`` is a flat ``{name: ndarray}`` dict (None for specs
    whose builder needs no weights)."""
    import numpy as np
    d = _version_dir(share_dir, model, version)
    os.makedirs(d, exist_ok=True)
    if params is not None:
        tmp = os.path.join(d, f"{_WEIGHTS}.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            np.savez(f, **{k: np.asarray(v) for k, v in params.items()})
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(d, _WEIGHTS))
    spec = {"model": model, "version": version,
            "has_weights": params is not None, **spec}
    atomic_write(os.path.join(d, _SPEC), json.dumps(spec, indent=2))
    return d


def load(share_dir: str, model: str, version: int
         ) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]]]:
    """Read one committed artifact back: ``(spec, params)``."""
    import numpy as np
    d = _version_dir(share_dir, model, version)
    with open(os.path.join(d, _SPEC)) as f:
        spec = json.load(f)
    params = None
    if spec.get("has_weights"):
        with np.load(os.path.join(d, _WEIGHTS)) as z:
            params = {k: z[k] for k in z.files}
    return spec, params


def versions(share_dir: str, model: str) -> Dict[int, str]:
    """Committed versions on disk: ``{version: dir}`` (only dirs whose
    spec.json has landed — an in-flight publish is invisible)."""
    base = os.path.join(deploys_root(share_dir), model)
    out: Dict[int, str] = {}
    try:
        names = os.listdir(base)
    except OSError:
        return out
    for name in names:
        m = _VDIR_RE.match(name)
        d = os.path.join(base, name)
        if m and os.path.exists(os.path.join(d, _SPEC)):
            out[int(m.group(1))] = d
    return out


def resolve_builder(path: str) -> Callable:
    """``"package.module:callable"`` to the callable itself.  The
    worker trusts the share directory exactly as much as the execstore
    does (operator-owned path — the spec names code to run)."""
    if ":" not in path:
        raise ValueError(
            f"builder {path!r} must be 'module:callable'")
    mod_name, attr = path.split(":", 1)
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, attr, None)
    if not callable(fn):
        raise ValueError(f"builder {path!r} did not resolve to a "
                         "callable")
    return fn


def build_deploy_kwargs(spec: Dict[str, Any],
                        params: Optional[Dict[str, Any]]
                        ) -> Dict[str, Any]:
    """Run the spec's builder: the ``ModelRegistry.deploy`` kwargs for
    this version (net/jax_fn+params/model plus any model_kwargs the
    spec pins, e.g. ``max_batch_size`` — pinned so every worker pads
    to the SAME buckets and the execstore fingerprints line up)."""
    builder = resolve_builder(spec["builder"])
    kwargs = dict(builder(spec.get("args") or {}, params))
    for k, v in (spec.get("deploy_kwargs") or {}).items():
        kwargs.setdefault(k, v)
    if spec.get("warmup_shapes") is not None:
        kwargs.setdefault("warmup_shapes",
                          tuple(spec["warmup_shapes"]))
    if spec.get("mesh") is not None:
        # the mesh section is pinned at the spec's top level (like
        # warmup_shapes) so every worker carves identical sub-meshes
        # and the sharded executables' fingerprints line up across
        # the fleet — same partition rules, same store entry
        kwargs.setdefault("mesh", spec["mesh"])
    return kwargs
