"""Fleet serving worker: one process of the worker plane.

``python -m analytics_zoo_tpu.serving.fleet.worker --share DIR
--port-file PATH [--fake] [--registry-json '{...}']``

A worker is the existing single-process data plane — a
:class:`~..registry.ModelRegistry` with its bucketed executables,
coalescer, admission control and decode engines — behind a localhost
socket speaking :mod:`.protocol` frames.  It owns NO fleet state: what
it serves is whatever the share directory's committed artifacts say
(``activate`` ops name versions), so a crashed worker's replacement
rebuilds the serving set from disk + execstore, in milliseconds when
the store is warm.

Supervision contract (the PR 10 machinery, reused):

* ``ZOO_HEARTBEAT_FILE`` — touched from the accept loop (throttled),
  so a wedged front door reads stale and the watchdog SIGKILLs;
* ``ZOO_FLIGHTREC_DIR`` — per-process black box installed from env;
  spans/logs/metric snapshots land under ``rank{r}.i{inc}/`` where
  rank is ``ZOO_TPU_PROCESS_ID`` and the incarnation is
  ``ZOO_RESTART_COUNT`` (both exported by the fleet supervisor);
* ``ZOO_EXECSTORE_DIR`` — the shared store; a warm activate records
  zero ``backend_compile`` events (reported per activate, which is
  how the fleet drill gates it cross-process);
* ``ZOO_PAGER_RESIDENT`` — when set (an int), the worker's registry
  runs a weight pager with that resident budget: each worker pages
  independently over the SHARED execstore, so a density fleet keeps
  one on-disk copy of every executable while each worker holds only
  its own traffic's working set on device.  ``--registry-json
  '{"pager": {...}}'`` configures the full knob set and wins over
  the env;
* ``ZOO_FLEET_WIRE=json`` — pin this worker's NEGOTIATED reply wire
  to the v1 JSON encoding (it still decodes binary requests); the
  router's per-connection ``hello`` discovers this and keeps that
  connection on JSON — the fleet-wide escape hatch for the v2 binary
  wire, and how mixed-version fleets interoperate;
* ``ZOO_FLEET_MAX_FRAME`` — frame-size cap in bytes (default 256
  MiB); an oversize REPLY degrades to a structured error envelope
  carrying ``attempted_bytes`` instead of a dropped connection;
* the port file is written ATOMICALLY once the socket is listening —
  its presence is the router's readiness signal, and a restarted
  incarnation's fresh port lands the same way.

``--fake`` serves the same protocol with zero jax WORK — stub
builders only, no backend touched, no compile ever (the package root
still imports jax; that is import cost, not compute) — so the tier-1
supervisor/router tests run the whole fan-out/retry machinery in
seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ... import envcontract
from ...observability import flightrec, tracefleet
from ...observability import trace as trace_mod
from ...observability.log import get_logger
from ...observability.metrics import MetricsRegistry
from .. import execstore
from ..metrics import registry_collector
from ..registry import ModelRegistry
from . import artifact, protocol

_slog = get_logger("zoo.serving.fleet.worker")

_HB_MIN_INTERVAL_S = 0.5
_ACCEPT_TIMEOUT_S = 0.25


class ServingWorker:
    """The worker process body (module docstring)."""

    def __init__(self, share_dir: str, registry_kwargs: Optional[dict] = None,
                 fake: bool = False):
        self.share_dir = share_dir
        self.fake = fake
        # identity from the flightrec helpers — the SAME parse that
        # names this process's recorder directory and log stamps
        self.rank = flightrec._env_rank()
        self.incarnation = flightrec._env_incarnation()
        # every worker traces: finished registry spans land in the
        # flight recorder (the configure() finish hook), tail-sampled
        # exemplars in the tracer's bounded store, and a traced
        # request's reply piggybacks its span summary back to the
        # router (reply_trace in _handle).  setdefault: registry_json
        # is parsed JSON and can never carry a live tracer, but a
        # caller constructing in-process may
        self.tracer = trace_mod.Tracer(
            capacity=512, **trace_mod.tail_config_from_env())
        reg_kwargs = dict(registry_kwargs or {})
        reg_kwargs.setdefault("tracer", self.tracer)
        self.registry = ModelRegistry(**reg_kwargs)
        self.metrics = MetricsRegistry()
        self.metrics.register_collector(registry_collector(self.registry))
        self.metrics.register_collector(self.tracer.families)
        store = None if fake else execstore.current()
        if store is not None:
            self.metrics.register_collector(store.families)
        rec = flightrec.current()
        if rec is not None:
            rec.add_collector(self.metrics.collect)
        self._hb_path = envcontract.env_str("ZOO_HEARTBEAT_FILE")
        self._hb_last = 0.0
        self._compile_events: List[str] = []
        self._compile_hooked = False
        # v2 wire ceiling this worker will NEGOTIATE down to:
        # ZOO_FLEET_WIRE=json pins the fleet to the v1 JSON wire (the
        # negotiation-fallback test hook, and the escape hatch if a
        # binary-wire bug ever ships) — the worker still DECODES
        # either encoding regardless
        self.wire_max = (protocol.WIRE_JSON
                         if envcontract.env_str("ZOO_FLEET_WIRE") == "json"
                         else protocol.WIRE_BINARY)
        # load piggyback: serve-op in-flight count plus a throttled
        # residency snapshot, attached to every reply (and ping) so
        # the router's affinity view refreshes for free on the data
        # path instead of needing a polling control op
        self._inflight = 0
        self._load_lock = threading.Lock()
        self._res_cache: tuple = (0.0, None)
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._conn_threads: List[threading.Thread] = []
        # control ops dispatch through a table: the serve loop is a
        # zoolint hot entry, and the control plane (activate → deploy
        # → warmup) legitimately BLOCKS on compiles — the table keeps
        # cold control ops off the hot path, in the call graph the
        # analyzer sees exactly as in the code's intent
        self._control = {"activate": self._activate,
                         "promote": self._promote,
                         "undeploy": self._undeploy,
                         "ping": self._ping,
                         "metrics": self._metrics,
                         "shutdown": self._shutdown}

    # ---- supervision plumbing ----
    def _beat(self) -> None:
        if not self._hb_path:
            return
        now = time.monotonic()
        if now - self._hb_last < _HB_MIN_INTERVAL_S:
            return
        self._hb_last = now
        try:
            with open(self._hb_path, "a"):
                os.utime(self._hb_path, None)
        except OSError:
            pass  # an unwritable heartbeat must not kill serving

    def _hook_compiles(self) -> None:
        """Count ``backend_compile`` events so every activate reply can
        report exactly what XLA work it did — the cross-process
        zero-compile gate reads these numbers."""
        if self._compile_hooked or self.fake:
            return
        self._compile_hooked = True
        from jax._src import monitoring
        monitoring.register_event_duration_secs_listener(
            lambda key, dur, **kw: (
                self._compile_events.append(key)
                if "backend_compile" in key else None))

    # ---- socket plumbing ----
    def bind(self) -> int:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        s.listen(64)
        s.settimeout(_ACCEPT_TIMEOUT_S)
        self._listener = s
        return s.getsockname()[1]

    def serve_forever(self) -> None:
        """Accept loop (main thread): one thread per connection, a
        heartbeat touch per pass — the liveness signal the watchdog
        judges this process by."""
        assert self._listener is not None, "bind() first"
        while not self._stop.is_set():
            self._beat()
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us during shutdown
            conn.settimeout(None)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._conn_threads.append(t)
            # reap finished handlers so a long-lived worker's thread
            # list stays bounded
            self._conn_threads = [x for x in self._conn_threads
                                  if x.is_alive()]
        try:
            self._listener.close()
        except OSError:
            pass
        self.registry.shutdown()

    def _load_snapshot(self) -> Dict[str, Any]:
        """The per-reply load piggyback: in-flight serve ops plus the
        residency list, the latter recomputed at most every ~50ms (a
        dict walk, but not per-request at fleet QPS)."""
        now = time.monotonic()
        with self._load_lock:
            out = self._inflight
            ts, res = self._res_cache
            if res is not None and now - ts <= 0.05:
                return {"o": out, "r": res}
        res = self.registry.resident_models()
        with self._load_lock:
            self._res_cache = (now, res)
            out = self._inflight
        return {"o": out, "r": res}

    def _serve_conn(self, conn: socket.socket) -> None:
        """One connection's request/reply loop (a zoolint hot entry:
        this is the per-request path).  Frame errors and hangups end
        the connection; op errors travel back as structured error
        envelopes — the connection survives a shed request.

        The reply encoding is per-connection state: JSON until the
        peer negotiates the binary wire with a ``hello`` (whose REPLY
        is still JSON — the peer does not know the verdict yet);
        requests decode as whatever they arrived as, no negotiation
        needed (the payload's first byte discriminates)."""
        wire = protocol.WIRE_JSON
        try:
            while not self._stop.is_set():
                got = protocol.recv_envelope(conn)
                if got is None:
                    return  # clean hangup
                req, _, _ = got
                rid = req.get("id")
                op = req.get("op")
                if op == "hello":
                    agreed = min(int(req.get("wire", 1)), self.wire_max)
                    protocol.send_frame(conn, {
                        "id": rid, "ok": True,
                        "result": {"wire": agreed, "rank": self.rank}})
                    wire = agreed
                    continue
                resp = self._execute(req, rid)
                resp["load"] = self._load_snapshot()
                binary = (wire == protocol.WIRE_BINARY
                          and op in ("predict", "generate"))
                try:
                    protocol.send_envelope(conn, resp, binary=binary)
                except (TypeError, ValueError,
                        protocol.FrameError) as e:
                    # an unserializable or oversized RESULT must
                    # degrade to an error reply, not a dead connection
                    # the router reads as a worker crash (and retries
                    # into, killing a sibling with the same reply).
                    # Safe to send a second frame: both failures fire
                    # BEFORE any bytes hit the socket — a mid-send
                    # OSError stays fatal for exactly that reason.
                    err = {"error": type(e).__name__,
                           "message": f"unserializable response: {e}"}
                    attempted = getattr(e, "attempted_bytes", None)
                    if attempted is not None:
                        err["attempted_bytes"] = attempted
                        err["max_frame_bytes"] = \
                            protocol.max_frame_bytes()
                    protocol.send_frame(conn, {
                        "id": rid, "ok": False,
                        "load": self._load_snapshot(), "error": err})
                if op == "shutdown":
                    self._stop.set()
                    return
        except (protocol.FrameError, OSError):
            pass  # dropped peer: the router already treats it as dead
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ---- ops ----
    def _execute(self, req: Dict[str, Any],
                 rid: Any) -> Dict[str, Any]:
        """One op, balanced: the in-flight count rides every exit
        explicitly (the PR 6 seat-leak discipline, zoolint ZL702 —
        which is also why this lives OUTSIDE _serve_conn's transport
        try: a nested protected region would hide the balance from
        the exception-path CFG).  In-flight covers every op uniformly
        (control ops are rare and brief) with deliberately LOCK-FREE
        bare updates — the piggyback is a load HINT, the router's own
        outstanding count is the scheduling truth."""
        try:
            self._inflight += 1
            result = self._handle(req)
        except BaseException as e:  # noqa: BLE001 — every op failure
            # becomes a structured envelope; the router re-raises the
            # concrete class
            self._inflight -= 1
            return {"id": rid, "ok": False,
                    "error": protocol.encode_error(e)}
        else:
            self._inflight -= 1
            return {"id": rid, "ok": True, **result}

    def _handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "predict":
            x = protocol.decode_value(req["inputs"])
            out, info = self.registry.predict_ex(
                req["model"], x,
                deadline_ms=req.get("deadline_ms"),
                trace_id=req.get("trace_id"),
                priority_class=req.get("priority_class"))
            # results stay RAW arrays: send_envelope owns the encoding
            # (binary hoists them out-of-band; JSON b64s them) — a
            # pre-encoded __nd__ dict would ride the binary wire as
            # base64 TEXT and throw the savings away
            return self._serve_result(out, info, req.get("trace_id"))
        if op == "generate":
            prompts = protocol.decode_value(req["prompt_ids"])
            # sampling params cross the wire as json scalars; the same
            # (prompt, sampling, seed) through any worker of this
            # artifact replays the single-process registry's tokens
            # bit-exactly (the engine's fold_in RNG is process-free)
            out, info = self.registry.generate_ex(
                req["model"], prompts, req["max_new_tokens"],
                deadline_ms=req.get("deadline_ms"),
                trace_id=req.get("trace_id"),
                priority_class=req.get("priority_class"),
                eos_id=req.get("eos_id"),
                temperature=req.get("temperature", 0.0),
                top_k=req.get("top_k"), top_p=req.get("top_p"),
                seed=req.get("seed", 0))
            return self._serve_result(out, info, req.get("trace_id"))
        fn = self._control.get(op)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req)

    def _serve_result(self, out, info, trace_id) -> Dict[str, Any]:
        """Package a serve-op result, piggybacking the worker-side
        span summary when the request carried a ``trace_id`` — the
        trace twin of the ``load`` residency piggyback, so the router
        stitches the worker timeline under its ``worker_call`` with
        no extra round trip.  Untraced requests pay one None check."""
        resp: Dict[str, Any] = {"result": out, "info": info}
        if trace_id is not None:
            t = tracefleet.reply_trace(self.tracer, trace_id,
                                       rank=self.rank,
                                       inc=self.incarnation)
            if t is not None:
                resp["trace"] = t
        return resp

    def _promote(self, req: Dict[str, Any]) -> Dict[str, Any]:
        return {"result": {"version": self.registry.promote(
            req["model"])}}

    def _undeploy(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Retire one model: drain + close in the registry, which also
        detaches it from the pager and drops its spans — the worker's
        next scrape carries none of its series (the registry snapshot
        is the collector), so a cycling density fleet's exposition
        stays bounded by what is DEPLOYED, not by what ever was."""
        drained = self.registry.undeploy(
            req["model"],
            drain_timeout=float(req.get("drain_timeout", 10.0)))
        return {"result": {"model": req["model"], "drained": drained,
                           "rank": self.rank}}

    def _ping(self, req: Dict[str, Any]) -> Dict[str, Any]:
        return {"result": {"pid": os.getpid(), "rank": self.rank,
                           "incarnation": self.incarnation,
                           "models": self.registry.models()}}

    def _metrics(self, req: Dict[str, Any]) -> Dict[str, Any]:
        return {"result": {"text": self.metrics.render_prometheus()}}

    def _shutdown(self, req: Dict[str, Any]) -> Dict[str, Any]:
        return {"result": {"stopping": True}}

    def _activate(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Warm-before-swap activation of one committed artifact
        version: build from the share, warm to completion (execstore
        read-through — zero compiles when the store already holds this
        fingerprint), then the registry's atomic pointer swap.  The
        old version keeps serving until the swap, so a rolling upgrade
        never shows this worker cold."""
        self._hook_compiles()
        model, version = req["model"], int(req["version"])
        spec, params = artifact.load(self.share_dir, model, version)
        kwargs = artifact.build_deploy_kwargs(spec, params)
        if req.get("canary_fraction") is not None:
            kwargs["canary_fraction"] = req["canary_fraction"]
        store = None if self.fake else execstore.current()
        s0 = store.stats() if store is not None else {}
        c0, t0 = len(self._compile_events), time.perf_counter()
        v = self.registry.deploy(model, version=version, **kwargs)
        warm_ms = round((time.perf_counter() - t0) * 1e3, 3)
        compiles = len(self._compile_events) - c0
        # the store hit/miss DELTA is the authoritative warm/cold
        # verdict for this activation: a decode-capable deployment
        # always fires a few trivial fill "compiles" allocating its
        # slot-array state (PERF_NOTES §PR 8 — state allocation, not
        # plan compilation), so misses==0 is the cross-process
        # zero-PLAN-compile claim; the raw compile count stays exact
        # for pure predict-plane deploys
        s1 = store.stats() if store is not None else {}
        hits = s1.get("hit", 0) - s0.get("hit", 0)
        misses = s1.get("miss", 0) - s0.get("miss", 0)
        _slog.info("fleet_activate", model=model, version=v,
                   compiles=compiles, warm_ms=warm_ms, rank=self.rank,
                   store_hits=hits, store_misses=misses)
        return {"result": {"version": v, "compiles": compiles,
                           "store_hits": hits, "store_misses": misses,
                           "warm_ms": warm_ms, "rank": self.rank}}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving.fleet.worker",
        description="fleet serving worker (module docstring)")
    ap.add_argument("--share", required=True,
                    help="shared fleet directory (artifacts live under "
                         "deploys/, the execstore wherever "
                         "ZOO_EXECSTORE_DIR points)")
    ap.add_argument("--port-file", required=True,
                    help="written atomically with the bound port once "
                         "the worker is listening (readiness signal)")
    ap.add_argument("--registry-json", default=None,
                    help="ModelRegistry kwargs as JSON")
    ap.add_argument("--fake", action="store_true",
                    help="serve stub builders only, never import jax "
                         "(test mode)")
    args = ap.parse_args(argv)

    flightrec.install_from_env()
    reg_kwargs = json.loads(args.registry_json) if args.registry_json \
        else {}
    pager_env = envcontract.env_str("ZOO_PAGER_RESIDENT")
    if pager_env and "pager" not in reg_kwargs:
        try:
            reg_kwargs["pager"] = {"max_resident": int(pager_env)}
        except ValueError:
            _slog.error("fleet_worker_bad_pager_env", value=pager_env)
    worker = ServingWorker(args.share, registry_kwargs=reg_kwargs,
                           fake=args.fake)
    if not args.fake:
        # touch jax early so import cost lands before readiness, and
        # the compile listener sees every event from the first activate
        worker._hook_compiles()
    port = worker.bind()
    flightrec.atomic_write(args.port_file, str(port))
    _slog.info("fleet_worker_up", rank=worker.rank,
               incarnation=worker.incarnation, port=port,
               fake=worker.fake, pid=os.getpid())
    try:
        worker.serve_forever()
    finally:
        flightrec.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
