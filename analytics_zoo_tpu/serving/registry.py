"""ModelRegistry: named, versioned models with zero-downtime hot-swap,
canary traffic splitting, and per-model admission control.

The control plane over PR 1's per-model data plane (bucketed
executables + request coalescing in ``pipeline/inference``).  The
reference analog is the POJO serving API behind the web-service sample:
a process-wide, thread-safe serving surface whose value is the
LIFECYCLE around the compute — deploy, swap, shed, observe — not the
forward pass itself.

Deploy protocol (the zero-downtime contract)::

    registry.deploy("ncf", net, warmup_shapes=(2,))

1. a FRESH ``InferenceModel`` is built and loaded for the new version —
   the live version's executables are never touched;
2. ``warmup()`` AOT-compiles the new version's whole bucket ladder TO
   COMPLETION while the old version keeps serving — live traffic never
   pays a trace.  A replicated model (``replicas=``) compiles each
   bucket ONCE and places + primes the executable on EVERY replica
   before the swap, and the model's admission concurrency is re-scaled
   to ``max_concurrency * replicas``;
3. the active-version pointer is swapped atomically (one reference
   assignment; every request reads it exactly once, so each response is
   computed ENTIRELY by the old or entirely by the new version);
4. the old version's coalescer is closed, which DRAINS it: its queued
   requests complete on the old executables, then the dispatcher exits.

If step 1 or 2 fails, the new model is discarded and
:class:`~.errors.DeployError` is raised — the previous version was
never unplugged, so rollback is a no-op (it just keeps serving).

Every request passes the model's :class:`~.admission.AdmissionController`
(bounded queue, concurrency limit, deadline-aware shedding), and
``metrics()`` snapshots the whole plane: per-version latency
percentiles, admission/shed counters, swap counts, and the data plane's
own ``BucketStats`` re-exported per model.
"""

from __future__ import annotations

import datetime
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..observability import trace as _trace
from .admission import AdmissionController
from .errors import ColdStartTimeout, DeployError, ModelNotFound
from .metrics import Counters, LatencyWindow
from .pager import ModelPager, PageRecipe

_RETIRED_KEPT = 4  # retired versions whose metrics stay inspectable


class _Deployment:
    """One version of one model: the serving handle + its counters."""

    def __init__(self, version: int, model):
        self.version = version
        self.model = model
        self.state = "staged"  # staged -> active/canary -> retired
        self.latency = LatencyWindow()
        self.counters = Counters("requests", "errors")
        self.deployed_at = time.time()

    def stats(self) -> Dict[str, Any]:
        # deployed_at exports as ISO-8601 (UTC) — a raw epoch float in
        # a metrics payload is unreadable and timezone-ambiguous; the
        # uptime gauge is the number dashboards actually plot
        deployed_iso = datetime.datetime.fromtimestamp(
            self.deployed_at, datetime.timezone.utc).isoformat()
        return {"state": self.state, **self.counters.snapshot(),
                "deployed_at": deployed_iso,
                "uptime_s": round(time.time() - self.deployed_at, 3),
                "latency": self.latency.snapshot()}


class _Entry:
    """Registry slot for one model name."""

    def __init__(self, name: str, admission: AdmissionController):
        self.name = name
        self.lock = threading.RLock()      # control-plane ops (brief)
        self.route_lock = threading.Lock()  # canary accumulator only
        # serializes whole deploys (build -> warmup -> swap), which can
        # take seconds: without it two racing deploys could swap in
        # either order, leaving the OLDER version active.  Held only by
        # deploy(); never on the request path.
        self.deploy_lock = threading.Lock()
        self.admission = admission
        self.active: Optional[_Deployment] = None
        self.canary: Optional[_Deployment] = None
        self.canary_fraction = 0.0
        self._canary_acc = 0.0
        self.retired: List[_Deployment] = []
        self.swap_count = 0
        self.next_version = 1
        self.warmup_shapes = None
        self.warmup_dtypes = None
        # weight-pager residency (serving/pager.py).  pager_state is
        # None for unpaged entries — the ONE read the request path
        # pays; pager_stamp is the lock-free LRU clock (a plain
        # monotonic write per request); pager_gen invalidates in-
        # flight faults across deploy/undeploy; transitions themselves
        # happen under the pager's own condition, never here.
        self.pager_state = None
        self.pager_gen = 0
        self.pager_stamp = 0.0
        self.pager_recipe = None
        self.pager_counters = Counters(
            "fault_ok", "fault_timeout", "fault_error",
            "evict_idle", "evict_pressure")


class ModelRegistry:
    """Multi-model serving control plane (see module docstring).

    ``model_defaults`` are the ``InferenceModel`` constructor kwargs
    every deploy starts from (override per-deploy via ``**model_kwargs``);
    ``max_queue``/``max_concurrency``/``default_deadline_ms`` configure
    each model's admission controller.
    """

    def __init__(self, max_queue: int = 64, max_concurrency: int = 4,
                 default_deadline_ms: Optional[float] = None,
                 priority_classes: Optional[Dict[str, Any]] = None,
                 tracer=None, pager=None, **model_defaults: Any):
        self._max_queue = max_queue
        self._max_concurrency = max_concurrency
        self._default_deadline_ms = default_deadline_ms
        # per-tenant admission classes, applied to every model's
        # controller: {"name": (priority, weight)} or {"name":
        # {"priority": ..., "weight": ...}} — see AdmissionController
        self._priority_classes = priority_classes
        # optional observability.Tracer: when set, every predict_ex
        # carries a request span through admission and the data plane
        self.tracer = tracer
        # optional weight/executable pager (serving/pager.py): a
        # ModelPager, or its constructor kwargs as a dict (the form a
        # fleet worker's --registry-json reaches for) — e.g.
        # pager={"max_resident": 4, "idle_evict_s": 300}
        if pager is None or isinstance(pager, ModelPager):
            self._pager = pager
        else:
            self._pager = ModelPager(**dict(pager))
        if self._pager is not None:
            self._pager.start_reaper()
        self._model_defaults = {
            "supported_concurrent_num": 4, "max_batch_size": 32,
            "coalescing": True, "max_wait_ms": 2.0, **model_defaults}
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._closed = False

    # ---- lookup ----
    def _entry(self, name: str) -> _Entry:
        e = self._entries.get(name)
        if e is None:
            raise ModelNotFound(f"no model deployed under {name!r}",
                                model=name,
                                deployed=sorted(self._entries))
        return e

    def _ensure_entry(self, name: str) -> _Entry:
        with self._lock:
            if self._closed:
                raise DeployError("registry is shut down", model=name)
            e = self._entries.get(name)
            if e is None:
                e = _Entry(name, AdmissionController(
                    max_queue=self._max_queue,
                    max_concurrency=self._max_concurrency,
                    default_deadline_ms=self._default_deadline_ms,
                    classes=self._priority_classes))
                self._entries[name] = e
            return e

    def models(self) -> Dict[str, Optional[int]]:
        """name -> active version (None while only a canary is staged).
        Single read per entry: a concurrent undeploy/shutdown nulls
        ``e.active`` at any moment, and a check-then-deref here would
        crash the listing (zoolint ZL721)."""
        return {n: (dep.version if (dep := e.active) is not None
                    else None)
                for n, e in list(self._entries.items())}

    def resident_models(self) -> List[str]:
        """Models a request would serve WITHOUT a pager fault right
        now: active, and either unpaged (always on device) or pager
        state ``resident``.  Lock-free snapshot reads, same discipline
        as :meth:`models` — this is the residency the fleet worker
        piggybacks onto every reply for the router's affinity
        scoring, so it must cost one dict walk, never a lock."""
        return sorted(
            n for n, e in list(self._entries.items())
            if e.active is not None
            and e.pager_state in (None, "resident"))

    # ---- deploy / swap ----
    def deploy(self, name: str, net=None, *, jax_fn=None, params=None,
               model=None, version: Optional[int] = None,
               warmup_shapes=None, warmup_dtypes=None,
               quantize: Optional[bool] = None,
               canary_fraction: Optional[float] = None,
               pageable: bool = True,
               **model_kwargs: Any) -> int:
        """Deploy ``net`` (a KerasNet/ZooModel), ``jax_fn``+``params``
        (a raw jax forward), or a prebuilt serving handle (``model``,
        anything with predict/warmup/close/serving_stats) as a new
        version of ``name``.  Returns the version number.

        Warmup runs TO COMPLETION before the swap; on any build/warmup
        failure the previous version keeps serving and
        :class:`DeployError` is raised (rollback).  With
        ``canary_fraction`` the new version is STAGED, not swapped:
        that fraction of requests routes to it until ``promote(name)``
        or ``clear_canary(name)``.
        """
        if canary_fraction is not None:
            canary_fraction = float(canary_fraction)
            # NaN fails this check too (accumulator poison otherwise)
            if not 0.0 <= canary_fraction <= 1.0:
                raise ValueError(
                    f"canary_fraction must be in [0, 1], got "
                    f"{canary_fraction}")
        entry = self._ensure_entry(name)
        # serialize whole deploys for this name: versions are allocated
        # inside the lock, so swap order always matches version order
        with entry.deploy_lock:
            if (canary_fraction is not None and self._pager is not None
                    and entry.pager_state is not None):
                # a canary stages WITHOUT swapping the active version,
                # so there is no safe moment to detach a cold active
                # from the pager (its handle may be paged out right
                # now) — pin the entry resident first, explicitly.
                # Checked INSIDE deploy_lock: attach/detach happen
                # under it, so a racing pageable deploy cannot slip
                # this guard.
                raise DeployError(
                    f"canary staging is not supported on the paged "
                    f"entry {name!r} — redeploy with pageable=False "
                    "(pinning it resident) before staging a canary",
                    model=name)
            with entry.lock:
                if version is None:
                    version = entry.next_version
                entry.next_version = max(entry.next_version, version + 1)
            # snapshot: promote() swaps entry.active under entry.lock
            # (not deploy_lock), so a re-read here could null between
            # the check and the deref (ZL721 pattern, lock-exempt for
            # the lint but not for the race)
            _dep0 = entry.active
            active_v = _dep0.version if _dep0 is not None else None

            def fail(stage: str, e: BaseException):
                raise DeployError(
                    f"deploy of {name!r} v{version} failed during "
                    f"{stage} — rolled back (v{active_v} still serving)",
                    model=name, version=version, active_version=active_v,
                    stage=stage,
                    cause=f"{type(e).__name__}: {e}") from e

            # 1. build + load a fresh handle; the live one is never
            # touched
            prebuilt = model is not None
            eff_kwargs = {**self._model_defaults, **model_kwargs}
            if model is None:
                from ..pipeline.inference import InferenceModel
                # store_tag: every executable this deploy persists
                # carries the registry name it serves (stat --by-model)
                im = InferenceModel(store_tag=name, **eff_kwargs)
                try:
                    if net is not None:
                        im.load_keras_net(net, quantize=quantize)
                    elif jax_fn is not None:
                        im.load_jax(jax_fn, params)
                    else:
                        raise ValueError(
                            "deploy needs net=, jax_fn=+params=, or "
                            "model=")
                except BaseException as e:
                    im.close()
                    fail("load", e)
                model = im

            # 2. warmup to completion BEFORE the swap (deploy pays the
            # compiles, live traffic never does).  A duck-typed handle
            # without the bucketed fast path's `_cache` attr is asked
            # via its own warmup(); an InferenceModel whose cache is
            # off (bucketing=False / quantized) has no ladder to warm.
            shapes = (warmup_shapes if warmup_shapes is not None
                      else entry.warmup_shapes)
            dtypes = (warmup_dtypes if warmup_dtypes is not None
                      else entry.warmup_dtypes)
            warmable = (callable(getattr(model, "warmup", None))
                        and getattr(model, "_cache", True) is not None)
            if shapes is not None and warmable:
                try:
                    model.warmup(shapes, dtypes)
                except BaseException as e:
                    model.close()
                    fail("warmup", e)

            dep = _Deployment(version, model)

            # the pager's rebuild recipe is captured BEFORE the swap
            # (host copies of the weights while the fresh handle is
            # known-consistent); None when this deploy is not pageable
            recipe = None
            if (self._pager is not None and canary_fraction is None
                    and pageable and not prebuilt):
                recipe = self._build_recipe(
                    name, version, model, jax_fn, eff_kwargs,
                    shapes, dtypes)

            # 3. atomic pointer swap (or canary staging) + 4. drain old
            old = None
            stale = False
            with entry.lock:
                with self._lock:
                    # the registry may have shut down (or this name
                    # been undeployed) while we were building/warming —
                    # swapping into a popped entry would leak a live
                    # model nobody can ever close
                    stale = (self._closed
                             or self._entries.get(name) is not entry)
                if not stale:
                    if shapes is not None:
                        entry.warmup_shapes = shapes
                        entry.warmup_dtypes = dtypes
                    if canary_fraction is not None:
                        old = entry.canary
                        dep.state = "canary"
                        entry.canary = dep
                        entry.canary_fraction = float(canary_fraction)
                        # route_lock owns the accumulator (zoolint
                        # ZL401): resetting it under entry.lock alone
                        # races _route's += and loses the reset
                        with entry.route_lock:
                            entry._canary_acc = 0.0
                    else:
                        old = entry.active
                        dep.state = "active"
                        entry.active = dep  # THE swap: one assignment
                        self._scale_admission(entry, dep)
                        if old is not None:
                            entry.swap_count += 1
            if stale:
                model.close()
                raise DeployError(
                    f"{name!r} was undeployed (or the registry shut "
                    f"down) while v{version} was building — the new "
                    "version was discarded", model=name, version=version)
            if self._pager is not None and canary_fraction is None:
                if recipe is not None:
                    # the just-swapped version IS resident (freshly
                    # built); the generation bump inside invalidates
                    # any in-flight fault of the previous version
                    self._pager.note_swapped(name, entry, recipe)
                elif entry.pager_state is not None:
                    # the new version is not pageable: pin the entry
                    # resident from here on (safe — the swap installed
                    # a live handle)
                    self._pager.detach(name, entry)
            self._retire(entry, old)
        return version

    def _build_recipe(self, name: str, version: int, model, jax_fn,
                      eff_kwargs: Dict[str, Any], shapes, dtypes
                      ) -> Optional[PageRecipe]:
        """The host-side rebuild recipe for a just-built deployment —
        what a cold entry keeps instead of device memory — or None
        when the deploy cannot be paged (prebuilt/duck-typed handle,
        quantized, or decode-capable: a decode engine's slot-array
        state is live stream context, not pageable weights).

        The recipe's ``build()`` re-runs the fault-in fast path: ONE
        ``device_put`` of the host weights (``load_jax`` /
        ``load_graph`` hand the placed tree to the replica set, which
        aliases rather than re-copies — the PR 5 discipline) and a
        warmup whose executables rehydrate from the persistent store
        in milliseconds."""
        from ..pipeline.inference import InferenceModel
        if not isinstance(model, InferenceModel):
            return None
        if (getattr(model, "_quantize_flag", False)
                or model._decode_engine is not None):
            return None
        import jax
        import numpy as np

        def host_tree(tree):
            # explicit device_get: runs at deploy time, transfer-guard
            # visible, and the result is plain host numpy — a cold
            # model must pin zero device memory
            return jax.tree_util.tree_map(
                lambda a: np.asarray(jax.device_get(a)), tree)

        graph = host_state = None
        if jax_fn is not None:
            host_params = host_tree(model._params)
        elif model._graph is not None:
            graph = model._graph
            host_params = host_tree(model._params)
            host_state = host_tree(model._state)
        else:
            return None
        host_bytes = sum(
            int(getattr(a, "nbytes", 0)) for a in
            jax.tree_util.tree_leaves((host_params, host_state)))
        warm = shapes is not None and model._cache is not None

        # distinct def name on purpose: generation.py's plan cache
        # calls a local `build()`, and zoolint's name-based hot graph
        # would weld that hot edge onto this cold deploy-shaped path
        def _page_rebuild(span=None):
            im = InferenceModel(store_tag=name, **eff_kwargs)
            try:
                if span is not None:
                    span.phase_start("weights_h2d")
                if graph is None:
                    im.load_jax(jax_fn, host_params)
                else:
                    im.load_graph(graph, host_params, host_state)
                if warm:
                    if span is not None:
                        span.phase_start("exec_rehydrate")
                    im.warmup(shapes, dtypes)
            except BaseException:
                im.close()
                raise
            finally:
                if span is not None:
                    span.phase_end()
            return im

        return PageRecipe(_page_rebuild, host_bytes=host_bytes,
                          version=version)

    def _scale_admission(self, entry: _Entry, dep: _Deployment):
        """Admission concurrency follows the ACTIVE version's replica
        count: N device replicas carry N times the concurrent work, so
        the per-model bound is base * replicas (reset to base when an
        un-replicated version activates).  Only activation re-scales —
        a staged canary must not re-bound the traffic the active
        version is still serving."""
        reps = getattr(dep.model, "n_replicas", 1) or 1
        entry.admission.set_max_concurrency(self._max_concurrency * reps)
        # the service-time EWMA describes the version that just
        # RETIRED: carrying a slow old model's estimate forward would
        # predictively shed deadline requests the fast new version
        # could meet (and vice versa hides real slowness behind a
        # stale fast estimate) — every activation starts clean
        entry.admission.reset_service_ewma()

    def promote(self, name: str) -> int:
        """Make the staged canary the active version (atomic swap,
        then drain the displaced one).  Returns the promoted version."""
        entry = self._entry(name)
        with entry.lock:
            dep = entry.canary
            if dep is None:
                raise ModelNotFound(f"no canary staged for {name!r}",
                                    model=name)
            old = entry.active
            dep.state = "active"
            entry.active = dep
            entry.canary = None
            entry.canary_fraction = 0.0
            self._scale_admission(entry, dep)
            if old is not None:
                entry.swap_count += 1
        self._retire(entry, old)
        return dep.version

    def clear_canary(self, name: str):
        """Discard the staged canary (the experiment failed)."""
        entry = self._entry(name)
        with entry.lock:
            dep = entry.canary
            entry.canary = None
            entry.canary_fraction = 0.0
        self._retire(entry, dep)

    def _retire(self, entry: _Entry, dep: Optional[_Deployment]):
        """Close a displaced deployment OUTSIDE the entry lock: close()
        drains its coalescer (queued requests complete on the old
        executables), which can take up to the drain timeout."""
        if dep is None:
            return
        # snapshot: the pager may null dep.model concurrently (a
        # paged-out deployment has no handle to close)
        retiring = dep.model
        if retiring is not None:
            retiring.close()
        with entry.lock:
            # state flips under entry.lock like every other state write
            # (zoolint ZL401); until the drain above finishes the
            # deployment truthfully still reads as serving
            dep.state = "retired"
            entry.retired.append(dep)
            del entry.retired[:-_RETIRED_KEPT]

    # ---- serving ----
    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                priority_class: Optional[str] = None):
        out, _ = self.predict_ex(name, inputs, deadline_ms=deadline_ms,
                                 priority_class=priority_class)
        return out

    def predict_ex(self, name: str, inputs,
                   deadline_ms: Optional[float] = None,
                   trace_id: Optional[str] = None,
                   priority_class: Optional[str] = None
                   ) -> Tuple[Any, Dict[str, Any]]:
        """predict + routing info ``{"model", "version", "canary"}`` —
        the web frontend tags responses with the serving version so
        clients (and the hot-swap tests) can see which side of a swap
        produced them.  Raises ModelNotFound / Overloaded /
        DeadlineExceeded (structured, immediate).

        With a tracer installed the request carries a span (id
        ``trace_id`` when given — the frontend passes X-Request-Id)
        through admission and the data plane; the span is activated for
        this thread and handed across the coalescer explicitly, and
        ``info`` gains ``request_id``.  Shed/failed requests finish
        their span too, labeled with the error type.

        ``priority_class`` tags the request for the admission
        controller's shedding order and weighted fair share (the
        registry's ``priority_classes`` config names the classes)."""
        return self._serve_ex(
            name, "predict", lambda model: model.predict(inputs),
            deadline_ms=deadline_ms, trace_id=trace_id,
            priority_class=priority_class)

    def _serve_ex(self, name: str, op: str, call,
                  deadline_ms: Optional[float] = None,
                  trace_id: Optional[str] = None,
                  priority_class: Optional[str] = None
                  ) -> Tuple[Any, Dict[str, Any]]:
        """The shared serve envelope — span + admission + canary
        routing + per-version counters/latency around ONE data-plane
        ``call(model)`` — used by both :meth:`predict_ex` and
        :meth:`generate_ex` so the two paths can never drift in
        admission or span semantics."""
        entry = self._entry(name)
        tracer = self.tracer
        span = (tracer.start_span(op, trace_id=trace_id, model=name)
                if tracer is not None else None)
        # the pager deadline shares the admission clock: a faulting
        # request queues under ITS deadline (admission wait included),
        # never a separate cold-start budget.  Computed only when a
        # pager exists — the unpaged request path stays untouched.
        pager_deadline = None
        if self._pager is not None:
            eff_deadline_ms = (deadline_ms if deadline_ms is not None
                               else entry.admission.default_deadline_ms)
            if eff_deadline_ms is not None:
                pager_deadline = (time.perf_counter()
                                  + eff_deadline_ms / 1e3)
        try:
            with _trace.activate(span), \
                    entry.admission.admit(deadline_ms=deadline_ms,
                                          span=span,
                                          priority_class=priority_class
                                          ) as grant:
                dep, is_canary = self._route(entry)
                if self._pager is not None \
                        and entry.pager_state is not None:
                    dep = self._pager_serve(entry, dep, pager_deadline,
                                            span, grant)
                if span is not None:
                    span.set_label("version", dep.version)
                    if is_canary:
                        span.set_label("canary", True)
                t0 = time.perf_counter()
                try:
                    out = call(dep.model)
                except BaseException:
                    dep.counters.inc("errors")
                    raise
                dep.latency.add(time.perf_counter() - t0)
                dep.counters.inc("requests")
        except BaseException as e:
            if span is not None:
                span.set_label("error", type(e).__name__)
            raise
        finally:
            if span is not None:
                span.finish()
        info = {"model": name, "version": dep.version,
                "canary": is_canary}
        if span is not None:
            info["request_id"] = span.trace_id
        return out, info

    def _pager_serve(self, entry: _Entry, dep: _Deployment,
                     deadline: Optional[float], span, grant
                     ) -> _Deployment:
        """Residency checkout for one admitted request.  The RESIDENT
        fast path is one state read, a lock-free LRU stamp, and the
        in-flight counter the evictor's quiesce reads — it NEVER
        touches the pager lock (the density bench pins this).  Any
        other state diverts to the shared fault-in, whose wait/build
        seconds are excluded from the admission service EWMA so a
        cold start cannot poison predictive shedding."""
        pager = self._pager
        for _ in range(32):
            entry.pager_stamp = time.monotonic()
            dep.counters.inc("started")
            if entry.pager_state == "resident":
                return dep
            # not usable: balance the in-flight accounting and fault.
            # The EWMA exclusion lives in a finally: the raise paths
            # (waiter deadline lapse, late fault, ColdStartTimeout)
            # spend the SAME wall time, and admission's error-path
            # release folds service time into the EWMA too — a timed-
            # out fault must not predictively shed the traffic behind
            # it any more than a served one
            dep.counters.inc("aborted")
            t_fault = time.perf_counter()
            try:
                pager.fault_in(entry, deadline=deadline, span=span)
            finally:
                if grant is not None:
                    grant.exclude_service_s(
                        time.perf_counter() - t_fault)
            dep, _ = self._route(entry)
            if entry.pager_state is None:
                # detached mid-flight (undeploy or a redeploy that
                # pinned the entry): serve unpaged if a live handle
                # exists, else the model is gone
                if dep.model is None:
                    raise ModelNotFound(
                        f"model {entry.name!r} was undeployed while "
                        "cold", model=entry.name)
                return dep
        # the thrash 503 is an SLO miss like any other: it must move
        # the timeout counter the alerting docs point at
        entry.pager_counters.inc("fault_timeout")
        raise ColdStartTimeout(
            f"model {entry.name!r} kept being evicted before this "
            "request could run — the resident budget is too small for "
            "the concurrent working set", model=entry.name,
            thrash=True)

    def generate(self, name: str, prompt_ids, max_new_tokens,
                 deadline_ms: Optional[float] = None,
                 priority_class: Optional[str] = None,
                 eos_id: Optional[int] = None,
                 temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed=0):
        out, _ = self.generate_ex(name, prompt_ids, max_new_tokens,
                                  deadline_ms=deadline_ms,
                                  priority_class=priority_class,
                                  eos_id=eos_id,
                                  temperature=temperature, top_k=top_k,
                                  top_p=top_p, seed=seed)
        return out

    def generate_ex(self, name: str, prompt_ids, max_new_tokens,
                    deadline_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority_class: Optional[str] = None,
                    eos_id: Optional[int] = None,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None, seed=0
                    ) -> Tuple[Any, Dict[str, Any]]:
        """The continuous-batching generate path: same admission /
        routing / counters / span discipline as :meth:`predict_ex`,
        but the data plane is the model's ``DecodeEngine`` — the
        request joins the live slot array at the next decode step and
        streams until EOS or ``max_new_tokens``.  Returns (list of
        per-row continuation arrays, routing info).
        ``temperature``/``top_k``/``top_p``/``seed`` select per-slot
        sampling (greedy by default); a fixed (prompt, sampling,
        seed) tuple replays the same tokens on ANY deployment of the
        same weights — in this process or a fleet worker's.  The
        admission slot is held for the whole decode: a decoding
        request IS in-flight work, and releasing early would let
        max_concurrency overcommit the engine's queue.  Requires the
        deployment to have been built with ``decode_capacity``
        (raises RuntimeError otherwise)."""
        return self._serve_ex(
            name, "generate",
            lambda model: model.generate(prompt_ids, max_new_tokens,
                                         eos_id=eos_id,
                                         temperature=temperature,
                                         top_k=top_k, top_p=top_p,
                                         seed=seed),
            deadline_ms=deadline_ms, trace_id=trace_id,
            priority_class=priority_class)

    def _route(self, entry: _Entry) -> Tuple[_Deployment, bool]:
        """Pick the serving version.  Canary routing uses an error
        accumulator, not randomness: over any run of N requests the
        canary receives floor/ceil(N * fraction) of them exactly."""
        canary = entry.canary
        if canary is not None and entry.canary_fraction > 0.0:
            with entry.route_lock:
                # re-read under the lock: promote()/clear may have won
                if entry.canary is canary:
                    entry._canary_acc += entry.canary_fraction
                    if entry._canary_acc >= 1.0:
                        entry._canary_acc -= 1.0
                        return canary, True
        active = entry.active
        if active is None:
            raise ModelNotFound(
                f"model {entry.name!r} has no active version "
                "(canary-only — promote it first)", model=entry.name)
        return active, False

    # ---- lifecycle ----
    def undeploy(self, name: str, drain_timeout: float = 10.0) -> bool:
        """Remove ``name``: stop admitting, let admitted requests
        finish (graceful drain), then close every version.  Returns
        True when the drain completed within ``drain_timeout``.

        Observability is retired WITH the model: the pager forgets the
        entry (waking any queued faulters, whose in-flight rebuild is
        generation-invalidated and discarded), and the tracer's span
        ring drops this model's spans — a paged fleet cycling many
        models must not accumulate dead models' series or spans."""
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is None:
            raise ModelNotFound(f"no model deployed under {name!r}",
                                model=name)
        drained = entry.admission.drain(timeout=drain_timeout)
        # deploy_lock: an in-flight deploy either sees the popped entry
        # and discards its new model, or swaps before we get here — in
        # which case entry.active below IS that new model and we close
        # it.  Either way nothing leaks.
        with entry.deploy_lock:
            with entry.lock:
                deps = [d for d in (entry.active, entry.canary)
                        if d is not None]
                entry.active = entry.canary = None
                for d in deps:
                    d.state = "retired"
            if self._pager is not None:
                self._pager.detach(name, entry)
        for d in deps:
            m = d.model  # snapshot: paged-out deployments hold None
            if m is not None:
                m.close()
        tracer = self.tracer
        if tracer is not None and hasattr(tracer, "retire"):
            tracer.retire(model=name)
        return drained

    def shutdown(self, drain_timeout: float = 10.0):
        """Drain and close every model (idempotent)."""
        with self._lock:
            self._closed = True
            names = list(self._entries)
        for n in names:
            try:
                self.undeploy(n, drain_timeout=drain_timeout)
            except ModelNotFound:
                pass
        if self._pager is not None:
            self._pager.close()

    @property
    def pager(self) -> Optional[ModelPager]:
        """The registry's weight pager (None when paging is off)."""
        return self._pager

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # ---- observability ----
    def metrics(self, name: Optional[str] = None) -> Dict[str, Any]:
        """Point-in-time snapshot of the whole control plane (or one
        model): per-version request counts / error counts / latency
        percentiles, admission + shed counters, swap count, canary
        state, and the active version's data-plane ``serving_stats``
        (bucket hit/miss/compile counters, coalescer dispatch stats)."""
        entries = ({name: self._entry(name)} if name is not None
                   else dict(self._entries))
        out: Dict[str, Any] = {}
        for n, e in entries.items():
            with e.lock:
                active, canary = e.active, e.canary
                versions = {d.version: d.stats() for d in
                            (*e.retired, canary, active) if d is not None}
                canary_info = (None if canary is None else
                               {"version": canary.version,
                                "fraction": e.canary_fraction})
                swaps = e.swap_count
            # a paged-out deployment has no handle: snapshot the model
            # reference once (the pager may demote concurrently)
            m_active = active.model if active is not None else None
            serving = (m_active.serving_stats()
                       if m_active is not None
                       and hasattr(m_active, "serving_stats") else {})
            out[n] = {
                "active_version": active.version if active else None,
                "canary": canary_info,
                # flat copy of the routed fraction (0.0 when no canary)
                # so dashboards need not null-check the canary object
                "canary_fraction": (canary_info["fraction"]
                                    if canary_info else 0.0),
                "swap_count": swaps,
                "admission": e.admission.snapshot(),
                "versions": versions,
                "serving": serving,
            }
            pager_state = e.pager_state
            if self._pager is not None and pager_state is not None:
                # lock-free reads by design: a scrape must never
                # contend with (or count as) pager activity
                out[n]["pager"] = {
                    "state": pager_state,
                    "resident": pager_state == "resident",
                    "idle_s": round(
                        time.monotonic() - e.pager_stamp, 3),
                    **e.pager_counters.snapshot()}
        return out
