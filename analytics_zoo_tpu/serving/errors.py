"""Structured serving errors.

Every control-plane rejection is an explicit, typed, immediately-raised
error — never a late timeout.  Each carries machine-readable ``details``
and an ``http_status`` so a web frontend can map it to a response code
without string-matching (the web-service sample does exactly that).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class ServingError(RuntimeError):
    """Base class for control-plane errors.

    ``code`` is a stable machine-readable name (the class name),
    ``details`` a flat JSON-serializable dict of context fields.
    """

    http_status = 500

    def __init__(self, message: str, **details: Any):
        super().__init__(message)
        self.message = message
        self.details: Dict[str, Any] = details

    @property
    def code(self) -> str:
        return type(self).__name__

    def to_dict(self) -> Dict[str, Any]:
        return {"error": self.code, "message": self.message,
                **self.details}

    def __str__(self):
        extra = ", ".join(f"{k}={v}" for k, v in self.details.items())
        return self.message + (f" ({extra})" if extra else "")


class ModelNotFound(ServingError):
    """No deployed model under that name (or that version)."""

    http_status = 404


class Overloaded(ServingError):
    """Admission rejected the request: the model's bounded queue is
    full (or the controller is draining).  Back off and retry —
    queueing it anyway would only grow latency without bound."""

    http_status = 429


class DeadlineExceeded(ServingError):
    """The request's deadline cannot (or could not) be met.

    Raised at ADMISSION time when predicted queue wait + service time
    already overruns the deadline (``details['shed']`` is True — the
    request never consumed a slot), or while waiting for a concurrency
    slot when the deadline lapses.  Either way the caller learns
    immediately instead of timing out late."""

    http_status = 504


class ColdStartTimeout(ServingError):
    """A request to a paged-out (cold) model queued for its fault-in
    but the deadline lapsed before the weights/executables were
    resident again.

    Cold-start handling is admission-integrated: a faulting request
    QUEUES under its own deadline (it is legitimate, promised work —
    never shed for merely being cold) and only past that deadline does
    it fail, with this structured 503 instead of a generic late
    timeout.  The fault-in itself keeps running — the model still
    becomes resident for the next caller, so a retry after the
    suggested backoff normally lands hot."""

    http_status = 503


class WorkerUnavailable(ServingError):
    """No live, routable worker could take the request (whole plane
    restarting or dead).  503: back off and retry.

    Raised by the fleet router; it lives here with the other serving
    errors so the wire protocol can register it in its envelope
    round-trip table without importing the router (zoolint ZL802 pins
    the registration)."""

    http_status = 503


class DeployError(ServingError):
    """A deploy failed before the swap (build or warmup error).  The
    previously active version is untouched and keeps serving — this is
    the rollback path, and ``details`` names the version still live."""

    http_status = 500


def error_response(exc: BaseException) -> tuple[int, Dict[str, Any]]:
    """(http_status, json_payload) for any exception — structured for
    ServingErrors, a generic 400 otherwise."""
    if isinstance(exc, ServingError):
        return exc.http_status, exc.to_dict()
    return 400, {"error": type(exc).__name__, "message": str(exc)}
