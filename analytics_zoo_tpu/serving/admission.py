"""Admission control: bounded queueing, concurrency limiting, and
deadline-aware load shedding for one served model.

The naive failure mode this prevents (and PR 1's data plane still had):
under overload the coalescer queue grows without bound, every request
"succeeds" seconds too late, and by the time the client times out the
server has still done the work.  An ``AdmissionController`` makes
overload EXPLICIT and IMMEDIATE instead:

* the wait queue is bounded (``max_queue``) — request #Q+1 is rejected
  with a structured :class:`~.errors.Overloaded` in microseconds, not
  parked;
* at most ``max_concurrency`` requests occupy the data plane at once
  (the coalescer still packs them into shared dispatches underneath);
* a request with a deadline is SHED AT ADMISSION when the predicted
  queue wait + service time (an EWMA of observed service times) already
  overruns it — :class:`~.errors.DeadlineExceeded` with ``shed=True``,
  before it consumes any capacity.  A request whose deadline lapses
  while waiting for a slot is also failed immediately at lapse time;
* ``drain()`` is the graceful-shutdown half: stop admitting, let
  everything already admitted (queued or running) finish.

Usage::

    ac = AdmissionController(max_queue=64, max_concurrency=4)
    with ac.admit(deadline_ms=50):     # may raise Overloaded/DeadlineExceeded
        out = model.predict(x)
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from .errors import DeadlineExceeded, Overloaded
from .metrics import Counters


class AdmissionController:
    """Bounded queue + concurrency limit + deadline-aware shedding."""

    def __init__(self, max_queue: int = 64, max_concurrency: int = 4,
                 default_deadline_ms: Optional[float] = None,
                 ewma_alpha: float = 0.2):
        if max_queue < 1:
            # _waiting transiently covers a request about to take a
            # free slot, so the strict bound needs at least one seat
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_concurrency < 1:
            raise ValueError(
                f"max_concurrency must be >= 1, got {max_concurrency}")
        self.max_queue = int(max_queue)
        self.max_concurrency = int(max_concurrency)
        self.default_deadline_ms = default_deadline_ms
        self._alpha = float(ewma_alpha)
        self._cond = threading.Condition()
        self._waiting = 0            # admitted, waiting for a slot
        self._running = 0            # holding a concurrency slot
        self._queue_high_water = 0
        self._draining = False
        self._service_ewma_s: Optional[float] = None
        self.counters = Counters(
            "admitted", "completed", "errors", "shed_overload",
            "shed_deadline", "shed_draining", "deadline_lapsed")

    # ---- admission ----
    @contextlib.contextmanager
    def admit(self, deadline_ms: Optional[float] = None, span=None):
        """Admit (or shed) one request; run the service call in the
        ``with`` body.  Raises Overloaded / DeadlineExceeded instead of
        queueing hopeless work.  ``span`` (an observability trace span)
        gets the ``admission_queue`` phase: opened here, closed by
        whichever phase the data plane starts next — so queue wait and
        slot wait are attributed, gap-free, even when admission is
        instant."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if span is not None:
            span.phase_start("admission_queue")
        t0 = time.perf_counter()
        deadline = None if deadline_ms is None else t0 + deadline_ms / 1e3
        self._acquire(t0, deadline, deadline_ms)
        t_service = time.perf_counter()
        try:
            yield
        except BaseException:
            self._release(t_service, error=True)
            raise
        self._release(t_service, error=False)

    def _predicted_wait_s(self) -> Optional[float]:
        """Predicted time to COMPLETE a request admitted now: full
        rounds of service ahead of it in the queue, plus its own
        service.  None until a service time has been observed (the
        first requests are never predictively shed — there is nothing
        to predict from)."""
        if self._service_ewma_s is None:
            return None
        rounds_ahead = self._waiting / float(self.max_concurrency)
        return self._service_ewma_s * (rounds_ahead + 1.0)

    def _acquire(self, t0: float, deadline: Optional[float],
                 deadline_ms: Optional[float]):
        with self._cond:
            if self._draining:
                self.counters.inc("shed_draining")
                raise Overloaded("model is draining — not admitting",
                                 queue_depth=self._waiting,
                                 draining=True)
            if self._waiting >= self.max_queue:
                self.counters.inc("shed_overload")
                raise Overloaded(
                    "admission queue full",
                    queue_depth=self._waiting, max_queue=self.max_queue)
            if deadline is not None:
                est = self._predicted_wait_s()
                if est is not None and t0 + est > deadline:
                    self.counters.inc("shed_deadline")
                    raise DeadlineExceeded(
                        "deadline cannot be met at current queue depth",
                        shed=True,
                        predicted_ms=round(est * 1e3, 3),
                        deadline_ms=deadline_ms,
                        queue_depth=self._waiting)
            self._waiting += 1
            if self._waiting > self._queue_high_water:
                self._queue_high_water = self._waiting
            got_slot = False
            try:
                while self._running >= self.max_concurrency:
                    remaining = (None if deadline is None
                                 else deadline - time.perf_counter())
                    if remaining is not None and remaining <= 0:
                        self.counters.inc("deadline_lapsed")
                        raise DeadlineExceeded(
                            "deadline lapsed waiting for a slot",
                            shed=False,
                            waited_ms=round(
                                (time.perf_counter() - t0) * 1e3, 3),
                            deadline_ms=deadline_ms)
                    self._cond.wait(timeout=remaining)
                got_slot = True
            finally:
                self._waiting -= 1
                if got_slot:
                    self._running += 1
                    self.counters.inc("admitted")
                else:
                    # our departure may unblock drain()'s wait
                    self._cond.notify_all()

    def _release(self, t_service: float, error: bool):
        dt = time.perf_counter() - t_service
        with self._cond:
            self._running -= 1
            self.counters.inc("errors" if error else "completed")
            # errors count toward the EWMA too: a failing model still
            # consumes service time, and shedding must see that
            if self._service_ewma_s is None:
                self._service_ewma_s = dt
            else:
                self._service_ewma_s += self._alpha * (
                    dt - self._service_ewma_s)
            self._cond.notify_all()

    def set_max_concurrency(self, n: int):
        """Re-bound concurrent service (thread-safe).  The registry
        calls this when a deployed model's replica count changes — N
        device replicas carry N times the concurrent work of one, so
        the admission bound scales with them.  Raising the bound wakes
        queued waiters immediately; lowering it only throttles NEW
        admissions (requests already running finish normally)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"max_concurrency must be >= 1, got {n}")
        with self._cond:
            self.max_concurrency = n
            self._cond.notify_all()

    # ---- shutdown ----
    def drain(self, timeout: float = 10.0) -> bool:
        """Graceful shutdown: stop admitting NEW requests (they get
        Overloaded) but let everything already admitted — queued or
        running — finish.  Returns True when fully drained within
        ``timeout``."""
        end = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._waiting or self._running:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- introspection ----
    def snapshot(self) -> dict:
        with self._cond:
            c = self.counters.snapshot()
            c["shed"] = (c["shed_overload"] + c["shed_deadline"]
                         + c["shed_draining"] + c["deadline_lapsed"])
            return {
                "queue_depth": self._waiting,
                "running": self._running,
                "queue_high_water": self._queue_high_water,
                "max_queue": self.max_queue,
                "max_concurrency": self.max_concurrency,
                "draining": self._draining,
                "service_ewma_ms": (
                    None if self._service_ewma_s is None
                    else round(self._service_ewma_s * 1e3, 3)),
                **c,
            }
