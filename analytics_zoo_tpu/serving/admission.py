"""Admission control: bounded queueing, concurrency limiting,
priority-aware load shedding, and weighted fair-share scheduling for
one served model.

The naive failure mode this prevents (and PR 1's data plane still had):
under overload the coalescer queue grows without bound, every request
"succeeds" seconds too late, and by the time the client times out the
server has still done the work.  An ``AdmissionController`` makes
overload EXPLICIT and IMMEDIATE instead:

* the wait queue is bounded (``max_queue``) — request #Q+1 is rejected
  with a structured :class:`~.errors.Overloaded` in microseconds, not
  parked;
* at most ``max_concurrency`` requests occupy the data plane at once
  (the coalescer still packs them into shared dispatches underneath);
* a request with a deadline is SHED AT ADMISSION when the predicted
  queue wait + service time (an EWMA of observed service times) already
  overruns it — :class:`~.errors.DeadlineExceeded` with ``shed=True``,
  before it consumes any capacity.  A request whose deadline lapses
  while waiting for a slot is also failed immediately at lapse time;
* ``drain()`` is the graceful-shutdown half: stop admitting, let
  everything already admitted (queued or running) finish.

Mixed tenants add two orthogonal knobs, both per *priority class*
(``set_class(name, priority=, weight=)``, requests tag themselves via
``admit(priority_class=)``):

* **priority** governs SHEDDING: when the queue is full, an arriving
  request EVICTS the newest waiting request of the lowest class whose
  priority is strictly below its own (the evicted caller gets
  ``Overloaded`` with ``evicted=True``), so under sustained overload
  shed requests drain exclusively from the lowest class until it is
  exhausted — only then does shedding climb the ladder.  Equal
  priorities never evict each other (the classic bounded-queue reject
  applies), and per-class shed counts are exported
  (``zoo_shed_total{class=...}``).
* **weight** governs SCHEDULING: freed slots are granted by weighted
  fair queueing over the classes with waiters (per-class virtual time
  advancing by ``1/weight`` per grant), so a 0.9/0.1 split holds
  regardless of arrival ratios.  ``weight=0`` marks a best-effort
  class: it is granted slots only when no weighted class has waiters.
  Within a class, grants are FIFO.

Usage::

    ac = AdmissionController(max_queue=64, max_concurrency=4,
                             classes={"gold": (10, 0.9),
                                      "batch": (0, 0.1)})
    with ac.admit(deadline_ms=50, priority_class="gold"):
        out = model.predict(x)
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Any, Dict, Optional

from .errors import DeadlineExceeded, Overloaded
from .metrics import Counters

DEFAULT_CLASS = "default"

# auto-registration bound for UNKNOWN class names (request input is
# untrusted); configured classes via set_class() are never capped
_MAX_CLASSES = 64

# the sink class unknown names fold into PAST the cap: best-effort
# (priority 0, weight 0), never the default class — the default is a
# real tenant with a full 1.0 WFQ share, and an attacker cycling fresh
# names must not ride it
_OVERFLOW_CLASS = "__overflow__"

# ticket states (single transition each, under the controller's lock)
_WAITING, _GRANTED, _EVICTED = 0, 1, 2


class AdmissionGrant:
    """The handle ``admit()`` yields for the ``with`` body.

    ``exclude_service_s`` subtracts one-off, non-recurring seconds
    from what this request contributes to the service-time EWMA.  The
    weight pager uses it for cold-start fault-ins: the EWMA predicts
    STEADY-STATE service for deadline shedding, and one 100 ms weight
    fault recorded as service time would predictively shed every
    deadline request behind it against a cost they will never pay."""

    __slots__ = ("excluded_s",)

    def __init__(self):
        self.excluded_s = 0.0

    def exclude_service_s(self, seconds: float) -> None:
        self.excluded_s += max(0.0, float(seconds))


class _Ticket:
    """One queued admission request."""

    __slots__ = ("cls", "seq", "state")

    def __init__(self, cls: "_PriorityClass", seq: int):
        self.cls = cls
        self.seq = seq
        self.state = _WAITING


class _PriorityClass:
    """Per-class scheduling/shedding state.  All fields are owned by
    the controller's condition lock."""

    __slots__ = ("name", "priority", "weight", "vtime", "waiters",
                 "admitted", "shed")

    def __init__(self, name: str, priority: int, weight: float):
        self.name = name
        self.priority = int(priority)
        self.weight = float(weight)
        self.vtime = 0.0
        self.waiters: "collections.deque[_Ticket]" = collections.deque()
        self.admitted = 0
        self.shed = 0


class AdmissionController:
    """Bounded queue + concurrency limit + deadline-aware shedding +
    priority classes with weighted fair-share (module docstring)."""

    def __init__(self, max_queue: int = 64, max_concurrency: int = 4,
                 default_deadline_ms: Optional[float] = None,
                 ewma_alpha: float = 0.2,
                 classes: Optional[Dict[str, Any]] = None):
        if max_queue < 1:
            # _waiting transiently covers a request about to take a
            # free slot, so the strict bound needs at least one seat
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_concurrency < 1:
            raise ValueError(
                f"max_concurrency must be >= 1, got {max_concurrency}")
        self.max_queue = int(max_queue)
        self.max_concurrency = int(max_concurrency)
        self.default_deadline_ms = default_deadline_ms
        self._alpha = float(ewma_alpha)
        # reentrant: the grant/evict helpers re-enter the lock they
        # were called under, so every state write is LEXICALLY guarded
        # (zoolint ZL401 sees the with-block, and so does a reader)
        self._cond = threading.Condition(threading.RLock())
        self._waiting = 0            # admitted to the queue, no slot yet
        self._running = 0            # holding a concurrency slot
        self._queue_high_water = 0
        self._draining = False
        self._service_ewma_s: Optional[float] = None
        self._seq = itertools.count()
        self._vclock = 0.0  # floor for a class (re)entering the queue
        self._classes: Dict[str, _PriorityClass] = {}
        self.set_class(DEFAULT_CLASS)
        self.set_class(_OVERFLOW_CLASS, priority=0, weight=0.0)
        for name, spec in (classes or {}).items():
            if isinstance(spec, dict):
                self.set_class(name, **spec)
            else:
                prio, weight = spec
                self.set_class(name, priority=prio, weight=weight)
        self.counters = Counters(
            "admitted", "completed", "errors", "shed_overload",
            "shed_deadline", "shed_draining", "shed_evicted",
            "deadline_lapsed")

    # ---- priority classes ----
    def set_class(self, name: str, priority: int = 0,
                  weight: float = 1.0) -> None:
        """Register (or reconfigure) a priority class.  ``priority``
        orders shedding (higher survives longer), ``weight`` its fair
        share of freed slots (0 = best-effort)."""
        if weight < 0:
            raise ValueError(f"class weight must be >= 0, got {weight}")
        with self._cond:
            cls = self._classes.get(name)
            if cls is None:
                self._classes[name] = _PriorityClass(name, priority,
                                                     weight)
            else:
                cls.priority = int(priority)
                cls.weight = float(weight)

    def _class_for(self, name: Optional[str]) -> _PriorityClass:
        if name is None:
            return self._classes[DEFAULT_CLASS]
        cls = self._classes.get(name)
        if cls is None:
            if len(self._classes) >= _MAX_CLASSES:
                # class names arrive from UNTRUSTED request input (the
                # web sample passes {"class": ...} straight through):
                # past the cap, unknown names share the best-effort
                # overflow class instead of permanently allocating
                # per-name state and three labeled metric series each
                # — an attacker sending fresh names must not grow
                # memory, explode scrape cardinality, dilute
                # configured fair-share weights, or (via the default
                # class's 1.0 weight) out-schedule a configured tenant
                return self._classes[_OVERFLOW_CLASS]
            # unknown names degrade to BEST-EFFORT (priority 0, weight
            # 0) rather than erroring a live request path: an
            # unregistered (or typo'd, or abusive) name must never
            # out-schedule a configured tenant — a weight of 1.0 here
            # would hand any fresh name a bigger WFQ share than the
            # web sample's 0.9 premium class.  Register explicitly for
            # real tenant configs.
            cls = _PriorityClass(name, 0, 0.0)
            self._classes[name] = cls
        return cls

    # ---- admission ----
    @contextlib.contextmanager
    def admit(self, deadline_ms: Optional[float] = None, span=None,
              priority_class: Optional[str] = None):
        """Admit (or shed) one request; run the service call in the
        ``with`` body.  Raises Overloaded / DeadlineExceeded instead of
        queueing hopeless work.  ``span`` (an observability trace span)
        gets the ``admission_queue`` phase: opened here, closed by
        whichever phase the data plane starts next — so queue wait and
        slot wait are attributed, gap-free, even when admission is
        instant.  ``priority_class`` tags the request for shedding
        order and fair-share scheduling (default class when None).
        Yields an :class:`AdmissionGrant` (callers that ignore it are
        unchanged; the weight pager excludes cold-start fault seconds
        from the service EWMA through it)."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if span is not None:
            span.phase_start("admission_queue")
        t0 = time.perf_counter()
        deadline = None if deadline_ms is None else t0 + deadline_ms / 1e3
        self._acquire(t0, deadline, deadline_ms, priority_class)
        t_service = time.perf_counter()
        grant = AdmissionGrant()
        try:
            yield grant
        except BaseException:
            self._release(t_service, error=True,
                          excluded_s=grant.excluded_s)
            raise
        self._release(t_service, error=False,
                      excluded_s=grant.excluded_s)

    def _predicted_wait_s(self, cls: "_PriorityClass") -> Optional[float]:
        """Predicted time to COMPLETE a ``cls`` request admitted now:
        rounds of service ahead of it, plus its own service.  None
        until a service time has been observed (the first requests are
        never predictively shed — there is nothing to predict from).

        The estimate is CLASS-AWARE: WFQ grants ``cls`` a
        ``weight/total_weight`` share of freed slots, so a weighted
        request only waits behind its OWN class's queue scaled by the
        inverse of that share — a high-weight request behind a large
        low-weight backlog must not be shed on a FIFO estimate the
        scheduler will never make it pay (single default class: the
        share is 1 and this reduces to the original whole-queue
        formula).  Weight-0 (best-effort) requests really do wait
        behind everyone, so they keep the whole-queue estimate."""
        if self._service_ewma_s is None:
            return None
        if cls.weight > 0:
            total_w = sum(c.weight for c in self._classes.values()
                          if c.waiters and c.weight > 0)
            if not cls.waiters:
                total_w += cls.weight  # our arrival joins the set
            share = cls.weight / total_w
            ahead = len(cls.waiters) / share
        else:
            ahead = self._waiting
        rounds_ahead = ahead / float(self.max_concurrency)
        return self._service_ewma_s * (rounds_ahead + 1.0)

    def _evict_for(self, priority: int) -> bool:
        """Make room for an arriving request of ``priority`` by
        evicting the NEWEST waiter of the lowest class whose priority
        is strictly below it.  Returns True when a seat was freed.
        Strictly-below keeps equal-priority traffic honest: a full
        queue of peers rejects the newcomer (classic bounded-queue
        semantics), it never cannibalizes itself."""
        with self._cond:  # reentrant — callers already hold it
            victim_cls = None
            for cls in self._classes.values():
                if cls.waiters and cls.priority < priority and (
                        victim_cls is None
                        or cls.priority < victim_cls.priority):
                    victim_cls = cls
            if victim_cls is None:
                return False
            ticket = victim_cls.waiters.pop()  # newest: waited least
            ticket.state = _EVICTED
            self._waiting -= 1
            victim_cls.shed += 1
            self.counters.inc("shed_evicted")
            self._cond.notify_all()
            return True

    def _acquire(self, t0: float, deadline: Optional[float],
                 deadline_ms: Optional[float],
                 priority_class: Optional[str]):
        with self._cond:
            cls = self._class_for(priority_class)
            if self._draining:
                # drain closes admission for EVERY class — a gold
                # request must not evict queued work the drain promised
                # to finish (priority inversion under drain)
                cls.shed += 1
                self.counters.inc("shed_draining")
                raise Overloaded("model is draining — not admitting",
                                 queue_depth=self._waiting,
                                 priority_class=cls.name,
                                 draining=True)
            if deadline is not None:
                # predictive shed BEFORE any eviction: a deadline-doomed
                # arrival must not destroy a queued lower-priority
                # request only to shed itself one check later (eviction
                # does not shorten the wait — the evictor inherits the
                # freed seat, not the victim's queue position)
                est = self._predicted_wait_s(cls)
                if est is not None and t0 + est > deadline:
                    cls.shed += 1
                    self.counters.inc("shed_deadline")
                    raise DeadlineExceeded(
                        "deadline cannot be met at current queue depth",
                        shed=True,
                        predicted_ms=round(est * 1e3, 3),
                        deadline_ms=deadline_ms,
                        priority_class=cls.name,
                        queue_depth=self._waiting)
            if self._waiting >= self.max_queue \
                    and not self._evict_for(cls.priority):
                cls.shed += 1
                self.counters.inc("shed_overload")
                raise Overloaded(
                    "admission queue full",
                    queue_depth=self._waiting, max_queue=self.max_queue,
                    priority_class=cls.name)
            ticket = _Ticket(cls, next(self._seq))
            if not cls.waiters and cls.weight > 0:
                # a class (re)entering the queue starts at the virtual
                # clock floor — an idle class must not bank credit and
                # then monopolize the next burst
                cls.vtime = max(cls.vtime, self._vclock)
            cls.waiters.append(ticket)
            self._waiting += 1
            if self._waiting > self._queue_high_water:
                self._queue_high_water = self._waiting
            self._grant_locked()
            try:
                while ticket.state == _WAITING:
                    remaining = (None if deadline is None
                                 else deadline - time.perf_counter())
                    if remaining is not None and remaining <= 0:
                        cls.shed += 1
                        self.counters.inc("deadline_lapsed")
                        raise DeadlineExceeded(
                            "deadline lapsed waiting for a slot",
                            shed=False,
                            waited_ms=round(
                                (time.perf_counter() - t0) * 1e3, 3),
                            deadline_ms=deadline_ms,
                            priority_class=cls.name)
                    self._cond.wait(timeout=remaining)
            except BaseException:
                # ANY exception out of the wait (deadline above, or a
                # KeyboardInterrupt/injected exception delivered inside
                # Condition.wait) must not leak the queue seat — the
                # old pre-class code guaranteed this in a finally, and
                # a leaked _WAITING ticket would shrink max_queue
                # forever (or, once granted by a racing release, burn
                # a concurrency slot no _release ever returns)
                if ticket.state == _WAITING:
                    cls.waiters.remove(ticket)
                    self._waiting -= 1
                elif ticket.state == _GRANTED:
                    # granted between the exception and this cleanup:
                    # hand the slot straight back
                    self._running -= 1
                    self.counters.inc("errors")
                    self._grant_locked()
                # our departure may unblock drain()'s wait
                self._cond.notify_all()
                raise
            if ticket.state == _EVICTED:
                raise Overloaded(
                    "shed while queued: a higher-priority request "
                    "arrived at a full queue",
                    evicted=True, priority_class=cls.name,
                    queue_depth=self._waiting,
                    max_queue=self.max_queue)

    def _next_class(self) -> Optional[_PriorityClass]:
        """The class whose head waiter gets the next freed slot.
        Weighted fair queueing over classes with weight > 0 (smallest
        virtual time first; ties to the higher priority, then FIFO);
        weight-0 classes are best-effort — eligible only when no
        weighted class has waiters, ordered by priority then FIFO."""
        weighted = None
        best_effort = None
        for cls in self._classes.values():
            if not cls.waiters:
                continue
            if cls.weight > 0:
                key = (cls.vtime, -cls.priority, cls.waiters[0].seq)
                if weighted is None or key < weighted[0]:
                    weighted = (key, cls)
            else:
                key = (-cls.priority, cls.waiters[0].seq)
                if best_effort is None or key < best_effort[0]:
                    best_effort = (key, cls)
        if weighted is not None:
            return weighted[1]
        return best_effort[1] if best_effort is not None else None

    def _grant_locked(self):
        """Hand out every free slot (called under the lock whenever
        one may have appeared).  All grant-side bookkeeping lives here
        so arrival order, release order, and concurrency raises share
        one scheduling policy."""
        with self._cond:  # reentrant — callers already hold it
            granted = False
            while self._running < self.max_concurrency:
                cls = self._next_class()
                if cls is None:
                    break
                ticket = cls.waiters.popleft()
                ticket.state = _GRANTED
                self._waiting -= 1
                self._running += 1
                self.counters.inc("admitted")
                cls.admitted += 1
                if cls.weight > 0:
                    self._vclock = max(self._vclock, cls.vtime)
                    cls.vtime += 1.0 / cls.weight
                granted = True
            if granted:
                self._cond.notify_all()

    def _release(self, t_service: float, error: bool,
                 excluded_s: float = 0.0):
        # excluded seconds (a pager fault-in) are one-off setup, not
        # service: the EWMA must keep predicting the steady state
        dt = max(0.0, time.perf_counter() - t_service - excluded_s)
        with self._cond:
            self._running -= 1
            self.counters.inc("errors" if error else "completed")
            # errors count toward the EWMA too: a failing model still
            # consumes service time, and shedding must see that
            if self._service_ewma_s is None:
                self._service_ewma_s = dt
            else:
                self._service_ewma_s += self._alpha * (
                    dt - self._service_ewma_s)
            self._grant_locked()
            self._cond.notify_all()

    def set_max_concurrency(self, n: int):
        """Re-bound concurrent service (thread-safe).  The registry
        calls this when a deployed model's replica count changes — N
        device replicas carry N times the concurrent work of one, so
        the admission bound scales with them (the autoscaler re-bounds
        it on every scale event the same way).  Raising the bound
        grants queued waiters immediately; lowering it only throttles
        NEW grants (requests already running finish normally)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"max_concurrency must be >= 1, got {n}")
        with self._cond:
            self.max_concurrency = n
            self._grant_locked()
            self._cond.notify_all()

    def reset_service_ewma(self):
        """Forget the observed service-time EWMA.  The registry calls
        this on version ACTIVATION: the estimate describes the model
        that produced it, and a slow old version's EWMA would
        predictively shed deadline requests a fast new version could
        easily meet.  The first requests after a reset are never
        predictively shed (same cold-start rule as construction)."""
        with self._cond:
            self._service_ewma_s = None

    # ---- shutdown ----
    def drain(self, timeout: float = 10.0) -> bool:
        """Graceful shutdown: stop admitting NEW requests (they get
        Overloaded) but let everything already admitted — queued or
        running — finish.  Returns True when fully drained within
        ``timeout``."""
        end = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._waiting or self._running:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- introspection ----
    def snapshot(self) -> dict:
        with self._cond:
            c = self.counters.snapshot()
            c["shed"] = (c["shed_overload"] + c["shed_deadline"]
                         + c["shed_draining"] + c["shed_evicted"]
                         + c["deadline_lapsed"])
            classes = {
                cls.name: {"priority": cls.priority,
                           "weight": cls.weight,
                           "waiting": len(cls.waiters),
                           "admitted": cls.admitted,
                           "shed": cls.shed}
                for cls in self._classes.values()}
            return {
                "queue_depth": self._waiting,
                "running": self._running,
                "queue_high_water": self._queue_high_water,
                "max_queue": self.max_queue,
                "max_concurrency": self.max_concurrency,
                "draining": self._draining,
                "service_ewma_ms": (
                    None if self._service_ewma_s is None
                    else round(self._service_ewma_s * 1e3, 3)),
                "classes": classes,
                **c,
            }
