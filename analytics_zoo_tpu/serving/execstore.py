"""Persistent executable store: a content-addressed on-disk compile
cache for zero-compile cold start and instant fleet deploy.

Every fresh process pays the full bucket-ladder + decode-plan XLA
compile (~380 ms per executable, PERF_NOTES §PR 5) before it can
serve — a restarted worker or a newly provisioned replica is cold for
seconds.  PR 5 already proved the serialized-executable round trip
loads in ~3-10 ms with only the device assignment rewritten; this
module persists those bytes so the SECOND process (and every process
after it, on every machine sharing the store) warms from disk in
milliseconds instead of compiling:

* **Content-addressed.**  An entry's key is a SHA-256 fingerprint over
  everything that could change the compiled artifact: the lowered HLO
  module (which captures the model graph, the padded bucket / batch
  signature, and — for plans that close over weights — the weight
  values themselves), a digest of the weights when they are runtime
  ARGUMENTS (the replica forward), the jax + jaxlib version strings,
  the backend platform and device kind, ``XLA_FLAGS``, and any
  caller-supplied extras (the decode engine adds its
  ``(capacity, max_len, bucket)`` tuple).  A change to ANY ingredient
  lands on a different key — "stale" entries are simply never found.
* **Read-through / write-behind.**  The compile sites
  (:meth:`~..pipeline.inference.serving.ReplicaSet.ensure_compiled`
  and the decode engine's plan builder) consult the store at
  warmup/compile-miss time only; a hit rehydrates the executable, a
  miss compiles exactly as before and then persists the result.  The
  per-dispatch hot path never touches the store — lookups happen only
  where a compile would otherwise happen (tests pin this).
* **Corruption-safe, never wrong.**  Writes go to a temp file and are
  published with an atomic rename; every entry carries a SHA-256
  checksum of its payload verified on read.  A truncated, bit-flipped,
  or unpicklable entry is counted ``invalid``, deleted, and the caller
  silently falls back to a fresh compile — the store can cause a
  recompile, never a wrong executable.
* **Observable.**  ``zoo_execstore_{hit,miss,write,invalid,evicted}_total``
  counter families plus ``zoo_execstore_entries`` /
  ``zoo_execstore_bytes`` gauges (:meth:`ExecStore.families`), an
  ``execstore_load`` event on the active request span when a hit
  happens under one, and structured log lines for every store verdict.

Enabling the store::

    export ZOO_EXECSTORE_DIR=/var/cache/zoo-exec   # fleet recipe
    # or, programmatically:
    from analytics_zoo_tpu.serving import execstore
    execstore.configure("/var/cache/zoo-exec", byte_budget=2 << 30)

With the store enabled, ``ModelRegistry.deploy()`` and
``DecodeEngine.warmup()`` in a process whose store is warm record
ZERO ``backend_compile`` events (``bench.py coldstart`` gates this
across two real processes).  Without configuration the store is
entirely inert — no files, no lookups, identical serving behavior.

Hygiene: the store is size-capped LRU.  Reads bump an entry's mtime;
``gc()`` (also ``python -m analytics_zoo_tpu.serving.execstore gc``)
evicts oldest-mtime entries over the byte budget — but never an entry
this process itself wrote or loaded (a deploy's own executables must
not vanish under it).  ``stat`` prints the store table.

Entry format: one JSON header line (fingerprint, meta, payload
checksum) followed by the raw payload bytes — ``stat`` and
``entries()`` read headers alone.  Trust model: payloads are
deserialized executables (decode-plan payloads are pickles), so the
store directory must be trusted exactly like the model files
themselves — point it at an operator-owned path, not a world-writable
one.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import envcontract
from ..observability import trace as _trace
from ..observability.log import get_logger as _get_logger
from ..observability.metrics import Family

_slog = _get_logger("zoo.serving.execstore")

ENV_DIR = "ZOO_EXECSTORE_DIR"
ENV_BUDGET = "ZOO_EXECSTORE_BYTES"
_SUFFIX = ".zexe"

_COUNTER_KEYS = ("hit", "miss", "write", "invalid", "evicted")


def _runtime_parts(device=None) -> Tuple:
    """The environment half of every fingerprint: anything here
    changing means an on-disk executable may no longer load (or may
    load but compute differently), so it must land on a different
    key.  Split out as a function so tests can monkeypatch a version
    bump without reinstalling jax."""
    import jax
    import jaxlib
    if device is None:
        device = jax.local_devices()[0]
    return ("jax", jax.__version__, "jaxlib", jaxlib.__version__,
            "platform", getattr(device.client, "platform", "?"),
            "device_kind", getattr(device, "device_kind", "?"),
            "xla_flags", os.environ.get("XLA_FLAGS", ""))


def hlo_digest(lowered) -> str:
    """SHA-256 of a ``jax.jit(...).lower(...)`` result's HLO module
    TEXT — the graph/shape/dtype half of a fingerprint.  Lowering is
    a trace + HLO emission: it fires no ``backend_compile`` event, so
    hashing it keeps the store-hit path compile-free.  The text form
    deliberately, not the serialized proto: the proto embeds
    process-unique computation ids (two identical lowerings hash
    differently even in ONE process), while the text is stable for
    identical source.  Source locations in the module metadata rotate
    the key on a code edit — a benign recompile, never a stale hit.
    Large constants may be elided from the text, which is why every
    caller ALSO folds a :func:`params_digest` of the weights into its
    fingerprint."""
    try:
        text = lowered.compiler_ir(dialect="hlo").as_hlo_text()
    except Exception:  # older/newer IR surface: StableHLO text
        text = lowered.as_text()
    return hashlib.sha256(text.encode()).hexdigest()


def params_digest(tree) -> str:
    """SHA-256 over a param tree's leaf CONTENTS (+ shapes/dtypes).
    Needed when the weights are runtime arguments of the executable
    (the replica forward): the compiled code is then weight-agnostic,
    but the store key must still rotate on a weight change so a
    redeploy with new weights can never be answered by an entry
    recorded against old ones.  Explicit ``device_get`` — runs at
    deploy time, transfer-guard visible."""
    import jax
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(jax.device_get(leaf))
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def serialize_compiled(compiled) -> bytes:
    """A jax-level ``Compiled`` (from ``lower().compile()``) as store
    payload bytes: the executable's PJRT serialization plus the
    in/out pytree defs it needs to be callable again."""
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree), protocol=4)


def rehydrate(payload: bytes):
    """Store payload bytes back into a callable jax-level ``Compiled``
    — a LOAD, not a compile: no ``backend_compile`` event fires, and
    calling the result is bit-identical to calling the freshly
    compiled original (same binary).  Raises on any malformed payload
    (callers fall back to compiling)."""
    from jax.experimental import serialize_executable as _se
    ser, in_tree, out_tree = pickle.loads(payload)
    return _se.deserialize_and_load(ser, in_tree, out_tree)


class StoreEntry:
    """One verified store read: the payload bytes + writer metadata."""

    __slots__ = ("fingerprint", "payload", "meta")

    def __init__(self, fingerprint: str, payload: bytes,
                 meta: Dict[str, Any]):
        self.fingerprint = fingerprint
        self.payload = payload
        self.meta = meta


class ExecStore:
    """The on-disk store (module docstring).  Thread-safe: counter and
    protected-set mutations are lock-guarded; file publishes are
    atomic renames, so concurrent processes sharing one directory see
    whole entries or nothing."""

    def __init__(self, root: str, byte_budget: Optional[int] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.byte_budget = (None if byte_budget is None
                            else int(byte_budget))
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in _COUNTER_KEYS}
        # entries this process wrote OR loaded: its own deploy depends
        # on them, so gc() must never evict them out from under it
        self._protected: set = set()

    # ---- keys ----
    def fingerprint(self, *parts, device=None) -> str:
        """Content address over ``parts`` + the runtime environment
        (jax/jaxlib versions, platform, device kind, XLA_FLAGS)."""
        h = hashlib.sha256()
        for part in _runtime_parts(device) + parts:
            h.update(repr(part).encode())
            h.update(b"\x00")
        return h.hexdigest()

    def _path(self, fp: str) -> str:
        return os.path.join(self.root, fp + _SUFFIX)

    def _count(self, key: str, n: int = 1):
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    # ---- read-through ----
    def lookup(self, fp: str) -> Optional[StoreEntry]:
        """One store read: the verified entry for ``fp``, or None on a
        miss.  A present-but-corrupt entry (truncated, bit-flipped,
        unpicklable, checksum mismatch) counts ``invalid``, is
        deleted, and reads as a miss — the caller compiles.  A hit
        bumps the entry's mtime (the LRU clock), protects it from
        this process's gc, records an ``execstore_load`` event on the
        active request span, and logs a structured line."""
        path = self._path(fp)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            self._count("miss")
            _slog.info("execstore_miss", key=fp[:12])
            return None
        try:
            # entry = one JSON header line + raw payload bytes (see
            # put()); json.dumps escapes newlines, so the first \n is
            # always the split point
            nl = raw.index(b"\n")
            obj = json.loads(raw[:nl])
            payload = raw[nl + 1:]
            meta = obj["meta"]
            if hashlib.sha256(payload).hexdigest() != obj["sha256"]:
                raise ValueError("payload checksum mismatch")
        except Exception as e:  # noqa: BLE001 — any decode failure is
            # the same verdict: invalid, delete, recompile
            self.note_invalid(fp, e)
            return None
        try:
            os.utime(path)  # LRU touch; best-effort
        except OSError:
            pass
        with self._lock:
            self._protected.add(fp)
        self._count("hit")
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        span = _trace.current_span()
        if span is not None:
            span.event("execstore_load", key=fp[:12], ms=ms,
                       bytes=len(payload))
        _slog.info("execstore_hit", key=fp[:12], bytes=len(payload),
                   read_ms=ms)
        return StoreEntry(fp, payload, meta)

    def note_invalid(self, fp: str, error: BaseException):
        """Record (and remove) a corrupt/undecodable entry so the
        recompile's write-behind replaces it cleanly.  Also the hook
        rehydration callers use when the PAYLOAD decodes but the
        executable inside it will not load."""
        self._count("invalid")
        try:
            os.remove(self._path(fp))
        except OSError:
            pass
        _slog.error("execstore_invalid", key=fp[:12],
                    error=f"{type(error).__name__}: {error}")

    # ---- write-behind ----
    def put(self, fp: str, payload: bytes,
            meta: Optional[Dict[str, Any]] = None) -> bool:
        """Persist one entry: a small JSON header line (fingerprint,
        meta, payload checksum) followed by the raw payload bytes —
        ``stat``/``entries()`` read the header alone, never the
        payload — written to a temp file and published by atomic
        rename (a reader never sees a torn entry).  Returns False
        (and logs) instead of raising on I/O or meta-encoding failure
        — the store must never fail a deploy that just compiled
        successfully.  A configured byte budget triggers an inline gc
        after the write (compile-time path, never per-dispatch)."""
        meta = dict(meta or {})
        meta.setdefault("created_at", time.time())
        path = self._path(fp)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            blob = json.dumps(
                {"fingerprint": fp, "meta": meta,
                 "sha256": hashlib.sha256(payload).hexdigest()}
            ).encode("utf-8") + b"\n" + payload
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            _slog.error("execstore_write_failed", key=fp[:12],
                        error=f"{type(e).__name__}: {e}")
            return False
        with self._lock:
            self._protected.add(fp)
        self._count("write")
        _slog.info("execstore_write", key=fp[:12], bytes=len(blob),
                   kind=meta.get("kind", "?"))
        if self.byte_budget is not None:
            self.gc()
        return True

    # ---- hygiene ----
    def _scan(self) -> List[Tuple[float, int, str]]:
        """(mtime, size, fingerprint) for every entry on disk."""
        out = []
        try:
            with os.scandir(self.root) as it:
                for de in it:
                    if not de.name.endswith(_SUFFIX):
                        continue
                    try:
                        st = de.stat()
                    except OSError:
                        continue
                    out.append((st.st_mtime, st.st_size,
                                de.name[:-len(_SUFFIX)]))
        except OSError:
            pass
        return out

    def gc(self, byte_budget: Optional[int] = None) -> Dict[str, Any]:
        """Size-capped LRU eviction: drop oldest-mtime entries until
        the store fits ``byte_budget`` (default: the configured
        budget; no-op when neither is set).  Entries this process
        wrote or loaded are NEVER evicted — a running server's own
        deploy must survive its own gc; they still count toward the
        total, so a budget smaller than the live working set simply
        keeps the protected set and nothing else."""
        budget = self.byte_budget if byte_budget is None else int(byte_budget)
        entries = self._scan()
        total = sum(size for _, size, _ in entries)
        evicted = 0
        freed = 0
        if budget is not None:
            with self._lock:
                protected = set(self._protected)
            for mtime, size, fp in sorted(entries):
                if total <= budget:
                    break
                if fp in protected:
                    continue
                try:
                    os.remove(self._path(fp))
                except OSError:
                    continue
                evicted += 1
                freed += size
                total -= size
        if evicted:
            self._count("evicted", evicted)
            _slog.info("execstore_gc", evicted=evicted,
                       freed_bytes=freed, kept_bytes=total)
        return {"evicted": evicted, "freed_bytes": freed,
                "entries": len(entries) - evicted, "bytes": total}

    # ---- observability ----
    def stats(self) -> Dict[str, Any]:
        entries = self._scan()
        with self._lock:
            counters = dict(self._counters)
            protected = len(self._protected)
        return {"root": self.root, "entries": len(entries),
                "bytes": sum(size for _, size, _ in entries),
                "byte_budget": self.byte_budget,
                "protected": protected, **counters}

    def families(self) -> List[Family]:
        """Prometheus collector: plug into a MetricsRegistry."""
        s = self.stats()
        fams = [Family("counter", f"zoo_execstore_{k}_total",
                       _FAMILY_HELP[k], [({}, s[k])])
                for k in _COUNTER_KEYS]
        fams.append(Family("gauge", "zoo_execstore_entries",
                           "executables currently persisted in the "
                           "store", [({}, s["entries"])]))
        fams.append(Family("gauge", "zoo_execstore_bytes",
                           "total bytes on disk in the store",
                           [({}, s["bytes"])]))
        return fams

    def entries(self) -> List[Dict[str, Any]]:
        """Per-entry table for the ``stat`` CLI (newest first).  Reads
        each entry's JSON header LINE only — never the payload, so
        listing a budget-sized store moves kilobytes, not
        gigabytes."""
        out = []
        for mtime, size, fp in sorted(self._scan(), reverse=True):
            try:
                with open(self._path(fp), "rb") as f:
                    head = f.readline(1 << 16)
                meta = json.loads(head).get("meta", {})
                kind = meta.get("kind", "?")
                model = meta.get("model", "-")
                mesh = _mesh_label(meta.get("mesh"))
            except Exception:  # noqa: BLE001 — stat must never crash
                kind, model, mesh = "unreadable", "-", "-"
            out.append({"fingerprint": fp, "bytes": size,
                        "mtime": mtime, "kind": kind, "model": model,
                        "mesh": mesh})
        return out

    def by_mesh(self) -> Dict[str, Dict[str, int]]:
        """Entries/bytes aggregated by the writer's ``mesh`` meta tag
        (``axes`` x ``strategy``; ``-`` for single-device entries) —
        the sharded-serving operator's view of how much of the store
        each mesh layout occupies."""
        agg: Dict[str, Dict[str, int]] = {}
        for e in self.entries():
            row = agg.setdefault(e["mesh"], {"entries": 0, "bytes": 0})
            row["entries"] += 1
            row["bytes"] += e["bytes"]
        return agg

    def by_model(self) -> Dict[str, Dict[str, int]]:
        """Entries/bytes aggregated by the writer's ``model`` meta tag
        (the registry name the deploy served; ``-`` for untagged
        entries) — what a density fleet's operator reads to see which
        models the shared store keeps on disk."""
        agg: Dict[str, Dict[str, int]] = {}
        for e in self.entries():
            row = agg.setdefault(e["model"], {"entries": 0, "bytes": 0})
            row["entries"] += 1
            row["bytes"] += e["bytes"]
        return agg


def _mesh_label(mesh) -> str:
    """Collapse a header ``mesh`` meta dict to a stable short label
    for aggregation: ``tensor=2/tp`` (axes sorted by name).  ``-``
    for entries written by single-device sets."""
    if not isinstance(mesh, dict):
        return "-"
    axes = mesh.get("axes")
    parts = ",".join(f"{k}={v}" for k, v in sorted(axes.items())) \
        if isinstance(axes, dict) and axes else "?"
    return f"{parts}/{mesh.get('strategy', '?')}"


_FAMILY_HELP = {
    "hit": "executable store lookups answered from disk",
    "miss": "executable store lookups that fell through to a compile",
    "write": "executables persisted to the store",
    "invalid": "corrupt/undecodable store entries detected (each one "
               "fell back to a fresh compile)",
    "evicted": "entries removed by LRU gc",
}


# ---- process-wide configuration --------------------------------------
_cur_lock = threading.Lock()
_current: Optional[ExecStore] = None
_env_checked = False


def configure(root: str, byte_budget: Optional[int] = None) -> ExecStore:
    """Enable the store for this process (every compile site consults
    it from now on).  Returns the store."""
    global _current, _env_checked
    with _cur_lock:
        _current = ExecStore(root, byte_budget=byte_budget)
        _env_checked = True
        return _current


def disable():
    """Turn the store off for this process (files stay on disk)."""
    global _current, _env_checked
    with _cur_lock:
        _current = None
        _env_checked = True


def current() -> Optional[ExecStore]:
    """The process store, or None when disabled.  First call honors
    ``ZOO_EXECSTORE_DIR`` (+ optional ``ZOO_EXECSTORE_BYTES``) so a
    fleet worker enables the store with one environment variable and
    zero code."""
    global _current, _env_checked
    if _current is None and not _env_checked:
        with _cur_lock:
            if _current is None and not _env_checked:
                _env_checked = True
                root = envcontract.env_str(ENV_DIR)
                if root:
                    budget = envcontract.env_str(ENV_BUDGET)
                    _current = ExecStore(
                        root,
                        byte_budget=int(budget) if budget else None)
    return _current


# ---- CLI --------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    """``python -m analytics_zoo_tpu.serving.execstore gc|stat``."""
    import argparse
    # --root is accepted on BOTH sides of the subcommand (`--root X
    # stat` and `stat --root X`): SUPPRESS on the shared parent keeps
    # an absent sub-level flag from clobbering a top-level one
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--root", default=argparse.SUPPRESS,
                        help=f"store directory (default: ${ENV_DIR})")
    parser = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving.execstore",
        description="inspect / garbage-collect the persistent "
                    "executable store")
    parser.add_argument("--root", default=None,
                        help=f"store directory (default: ${ENV_DIR})")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_stat = sub.add_parser("stat", parents=[common],
                            help="print store contents and counters")
    p_stat.add_argument("--by-model", action="store_true",
                        help="aggregate entries/bytes per model tag "
                             "(the registry name each deploy wrote)")
    p_stat.add_argument("--by-mesh", action="store_true",
                        help="aggregate entries/bytes per mesh layout "
                             "(axes x strategy; '-' = single-device)")
    p_gc = sub.add_parser("gc", parents=[common],
                          help="LRU-evict down to a byte budget")
    p_gc.add_argument("--budget", type=int, default=None,
                      help=f"byte budget (default: ${ENV_BUDGET})")
    args = parser.parse_args(argv)
    root = args.root or envcontract.env_str(ENV_DIR)
    if not root:
        parser.error(f"no store: pass --root or set ${ENV_DIR}")
    store = ExecStore(root)
    if args.cmd == "stat":
        s = store.stats()
        print(f"execstore {s['root']}: {s['entries']} entries, "
              f"{s['bytes']:,} bytes"
              + (f" (budget {s['byte_budget']:,})"
                 if s["byte_budget"] else ""))
        if getattr(args, "by_model", False) \
                or getattr(args, "by_mesh", False):
            # largest first: the density question is "what is eating
            # the store", answered top-down
            table = store.by_mesh() if getattr(args, "by_mesh", False) \
                else store.by_model()
            agg = sorted(table.items(), key=lambda kv: -kv[1]["bytes"])
            for tag, row in agg:
                print(f"  {tag:<24} {row['entries']:>5} entries  "
                      f"{row['bytes']:>12,} B")
            return 0
        for e in store.entries():
            age = time.time() - e["mtime"]
            print(f"  {e['fingerprint'][:16]}  {e['bytes']:>10,} B  "
                  f"{age:>8.0f}s old  {e['kind']}  {e['model']}  "
                  f"{e['mesh']}")
        return 0
    budget = args.budget
    if budget is None:
        env_budget = envcontract.env_str(ENV_BUDGET)
        if env_budget is None:
            parser.error(f"gc needs --budget or ${ENV_BUDGET}")
        budget = int(env_budget)
    res = store.gc(byte_budget=budget)
    print(f"execstore gc: evicted {res['evicted']} entries "
          f"({res['freed_bytes']:,} B freed), {res['entries']} kept "
          f"({res['bytes']:,} B)")
    return 0


if __name__ == "__main__":  # pragma: no cover — tested via main()
    import sys
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stat | head closed the pipe — a normal way to read a long
        # table, not an error worth a traceback
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
