"""Weight/executable pager: serving density — one node serving many
more models than fit on-device.

Production fleets serve hundreds of models whose combined working set
exceeds device memory, but every ``ModelRegistry.deploy`` used to pin
its weights and executables forever.  The pager turns each registry
entry into a resident/cold state machine instead:

* **resident** — the deployment holds a live ``InferenceModel``
  (device-placed weights + compiled/rehydrated executables); requests
  serve on the existing hot path, which NEVER acquires the pager lock
  (the density bench pins zero pager-lock acquisitions and zero
  compiles over a warmed resident window);
* **cold** — the deployment's model handle is closed and dropped;
  the entry keeps only its *recipe*: host-side (numpy) weights plus
  the deploy configuration.  On-disk executables live in the
  persistent :mod:`.execstore` under the same fingerprints the deploy
  wrote, so nothing but the weights needs to survive in RAM;
* **faulting** — the first request to a cold model rebuilds the
  handle: one ``device_put`` of the host weights (the placed-tree
  discipline of ``InferenceModel.load_jax`` — replica 0 aliases the
  placed buffers, never a second device copy) plus an execstore
  rehydrate of every bucket executable (~ms, zero compiles when the
  store is warm).  Concurrent first-requests to the same model share
  ONE fault: the winner builds, the rest wait on the pager condition
  (``pager_wait`` span phase) — no duplicate ``device_put``;
* **evicting** — idle-time or memory-pressure demotion back to cold.
  Eviction is in-flight-safe: arrivals are diverted to the fault path
  first, then the evictor waits for the deployment's in-flight
  balance (``started == aborted + requests + errors`` on the
  deployment counters — accounting the hot path already pays) to
  reach zero before closing the handle.  A model that will not
  quiesce within the bound is HOT: the eviction aborts and residency
  is restored.

Cold-start handling is admission-integrated: a faulting request holds
its admission slot and queues *under its own deadline* — past it the
request fails with the structured 503
:class:`~.errors.ColdStartTimeout` (the fault keeps running; the next
caller lands hot), and the fault seconds are EXCLUDED from the
admission controller's service-time EWMA so one cold start cannot
poison predictive deadline shedding for the requests behind it.

Observability: ``zoo_model_resident{model}``,
``zoo_pager_faults_total{model,outcome=ok|timeout|error}`` and
``zoo_pager_evictions_total{model,reason=idle|pressure}`` families
ride the registry scrape, and a faulting request's span carries the
``pager_wait`` / ``weights_h2d`` / ``exec_rehydrate`` phases.

Fleet recipe: every worker runs its own pager over the shared
execstore (``--registry-json '{"pager": {"max_resident": N}}'`` or
``ZOO_PAGER_RESIDENT=N``), so a density fleet keeps one on-disk copy
of every executable and each worker faults in only what its traffic
touches.  The router never retries a :class:`ColdStartTimeout` on a
sibling (structured serving errors are never retried), so one slow
fault cannot cascade into every worker faulting the same model.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..observability.log import get_logger as _get_logger
from .errors import ColdStartTimeout

_slog = _get_logger("zoo.serving.pager")

#: entry residency states (``entry.pager_state``; None = unpaged)
RESIDENT = "resident"
FAULTING = "faulting"
EVICTING = "evicting"
COLD = "cold"


class _CountingLock:
    """A plain mutex that counts successful acquisitions.  The density
    bench's resident-hot-path gate reads the count around a warmed
    serve window: a resident model's request path must never touch
    the pager, and this makes "never" measurable instead of asserted.
    (The increment happens while the lock is held, so the counter
    needs no lock of its own.)"""

    __slots__ = ("_lock", "acquisitions")

    def __init__(self):
        self._lock = threading.Lock()
        self.acquisitions = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self.acquisitions += 1
        return ok

    def release(self):
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self._lock.release()


class PageRecipe:
    """Everything needed to rebuild a cold deployment's serving handle
    from host memory + the execstore: a ``build()`` closure created at
    deploy time (it captures HOST-side numpy weights — never device
    arrays, or the cold state would still pin device memory) plus
    bookkeeping for logs and budgets."""

    __slots__ = ("build", "host_bytes", "version")

    def __init__(self, build: Callable[..., Any], host_bytes: int = 0,
                 version: int = 0):
        self.build = build
        self.host_bytes = int(host_bytes)
        self.version = int(version)


class ModelPager:
    """The LRU weight/executable pager one :class:`ModelRegistry` owns
    (module docstring).

    ``max_resident`` bounds how many paged models hold device memory
    at once (the pressure trigger: a fault past the budget evicts the
    least-recently-used resident entry first).  ``idle_evict_s``
    additionally demotes entries untouched for that long via a
    background reaper thread (off by default — pressure-only paging
    keeps the process thread-free and the resident hot window
    deterministic).  ``fault_timeout_s`` is the cold-start backstop
    for deadline-less requests; requests with a deadline queue under
    their own.
    """

    def __init__(self, max_resident: int, idle_evict_s: Optional[float] = None,
                 fault_timeout_s: float = 60.0,
                 quiesce_timeout_s: float = 5.0,
                 reap_interval_s: float = 0.5):
        if int(max_resident) < 1:
            raise ValueError(
                f"max_resident must be >= 1, got {max_resident}")
        self.max_resident = int(max_resident)
        self.idle_evict_s = (None if idle_evict_s is None
                             else float(idle_evict_s))
        self.fault_timeout_s = float(fault_timeout_s)
        self.quiesce_timeout_s = float(quiesce_timeout_s)
        self._reap_interval_s = float(reap_interval_s)
        # THE pager lock: every residency transition (fault, evict,
        # attach, detach) serializes here.  The resident request path
        # never acquires it — `lock_acquisitions` is the proof the
        # bench reads.
        self._lock = _CountingLock()
        self._cond = threading.Condition(self._lock)
        self._entries: Dict[str, Any] = {}
        self._reaper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False

    # ---- introspection -------------------------------------------------
    @property
    def lock_acquisitions(self) -> int:
        """Total pager-lock acquisitions ever (bench gate reads the
        delta over a warmed resident window and requires 0)."""
        return self._lock.acquisitions

    def resident_count(self) -> int:
        with self._cond:
            return sum(1 for e in self._entries.values()
                       if e.pager_state in (RESIDENT, FAULTING, EVICTING))

    def snapshot(self) -> Dict[str, Any]:
        """Control-plane view (NOT for the per-request path — this
        takes the pager lock)."""
        now = time.monotonic()
        with self._cond:
            models = {
                n: {"state": e.pager_state,
                    "idle_s": round(now - e.pager_stamp, 3),
                    **e.pager_counters.snapshot()}
                for n, e in sorted(self._entries.items())}
        return {"max_resident": self.max_resident,
                "idle_evict_s": self.idle_evict_s,
                "lock_acquisitions": self.lock_acquisitions,
                "models": models}

    # ---- registry hooks (control plane) --------------------------------
    def note_swapped(self, name: str, entry, recipe: PageRecipe):
        """A deploy just swapped a freshly-built (hence resident)
        version into ``entry``: record the new recipe, bump the
        generation so any in-flight fault of the PREVIOUS version
        discards its rebuild instead of installing stale weights, and
        make room under the budget."""
        with self._cond:
            self._entries[name] = entry
            entry.pager_gen += 1
            entry.pager_recipe = recipe
            entry.pager_state = RESIDENT
            entry.pager_stamp = time.monotonic()
            self._cond.notify_all()
        self._evict_for_budget(exclude=entry)

    def detach(self, name: str, entry) -> None:
        """Stop paging ``entry`` (undeploy, or a redeploy that is no
        longer pageable).  Waiting faulters wake and re-route; an
        in-flight rebuild sees the generation bump and closes its
        model instead of installing it."""
        with self._cond:
            self._entries.pop(name, None)
            entry.pager_gen += 1
            entry.pager_recipe = None
            entry.pager_state = None
            self._cond.notify_all()

    def close(self):
        """Stop the reaper (idempotent).  Does not touch residency —
        the registry's shutdown closes the models themselves."""
        self._closed = True
        self._stop.set()
        reaper = self._reaper
        if reaper is not None and reaper.is_alive():
            with self._cond:
                self._cond.notify_all()
            reaper.join(timeout=10.0)

    # ---- fault-in (the cold-request path) ------------------------------
    def fault_in(self, entry, deadline: Optional[float] = None,
                 span=None) -> float:
        """Bring ``entry`` resident (or wait for whoever already is).
        Returns the seconds this call spent waiting/building so the
        caller can exclude them from the admission EWMA.  Raises
        :class:`ColdStartTimeout` when ``deadline`` (absolute
        ``time.perf_counter()`` seconds; the pager's
        ``fault_timeout_s`` backstop when None) lapses first — the
        fault itself keeps running for the next caller."""
        t0 = time.perf_counter()
        if deadline is None:
            deadline = t0 + self.fault_timeout_s
        gen = 0
        with self._cond:
            while True:
                st = entry.pager_state
                if st is None or st == RESIDENT:
                    return time.perf_counter() - t0
                if st == COLD and entry.pager_recipe is not None:
                    entry.pager_state = FAULTING
                    gen = entry.pager_gen
                    break
                # someone else is faulting (or an eviction is mid-
                # teardown): queue under the deadline
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    entry.pager_counters.inc("fault_timeout")
                    raise ColdStartTimeout(
                        f"model {entry.name!r} is cold and its "
                        "fault-in did not complete within the deadline",
                        model=entry.name, state=st,
                        waited_ms=round(
                            (time.perf_counter() - t0) * 1e3, 3))
                if span is not None:
                    span.phase_start("pager_wait")
                self._cond.wait(timeout=remaining)
        # we are the faulter: build OUTSIDE the lock (waiters park on
        # the condition; the resident hot path never comes near it)
        return self._fault_build(entry, gen, t0, deadline, span)

    def _fault_build(self, entry, gen: int, t0: float, deadline: float,
                     span) -> float:
        self._evict_for_budget(exclude=entry)
        recipe = entry.pager_recipe
        dep0 = entry.active
        model = None
        try:
            if recipe is None or dep0 is None:
                raise RuntimeError(
                    f"model {entry.name!r} lost its page recipe "
                    "(undeployed mid-fault)")
            t_build = time.perf_counter()
            # indirect dispatch into the COLD build (the fleet
            # worker's control-table discipline): the rebuild blocks
            # on device placement + executable rehydrate by design —
            # that block IS the fault — and must not drag warmup's
            # compile-time sync into the hot serve loop's zoolint
            # call graph
            rebuild_cold = recipe.build
            model = rebuild_cold(span=span)
            # group-atomic fault: a sharded model whose replica-group
            # placement came back incomplete must FAIL the fault (the
            # entry stays cold, the requester gets the error) rather
            # than install — a partially-resident group serves wrong
            # answers, not slower ones
            check = getattr(model, "placement_complete", None)
            if check is not None and not check():
                raise RuntimeError(
                    f"model {entry.name!r} rebuilt with incomplete "
                    "replica-group placement — refusing to install a "
                    "partially resident group")
            build_s = time.perf_counter() - t_build
        except BaseException as e:
            with self._cond:
                if entry.pager_gen == gen and \
                        entry.pager_state == FAULTING:
                    entry.pager_state = COLD
                entry.pager_counters.inc("fault_error")
                self._cond.notify_all()
            _slog.error("pager_fault_failed", model=entry.name,
                        error=f"{type(e).__name__}: {e}")
            raise
        # install: only into the deployment the recipe describes.  A
        # deploy/undeploy that raced the build bumped the generation
        # (or re-pointed entry.active, or already re-populated
        # dep0.model) — then this rebuild is stale and must be closed,
        # never swapped over fresher weights.
        stale = False
        with entry.lock:
            if (entry.pager_gen != gen or entry.active is not dep0
                    or dep0.model is not None):
                stale = True
            else:
                dep0.model = model
        if stale:
            model.close()
            with self._cond:
                self._cond.notify_all()
            _slog.info("pager_fault_stale", model=entry.name)
            return time.perf_counter() - t0
        # ONE outcome per requesting thread: a fault that completed
        # past the requester's deadline counts `timeout`, not `ok` —
        # the request was NOT served, however useful the install is
        # to the next caller (sum-over-outcomes must equal requests)
        late = time.perf_counter() > deadline
        with self._cond:
            if entry.pager_gen == gen and entry.pager_state == FAULTING:
                entry.pager_state = RESIDENT
            entry.pager_stamp = time.monotonic()
            entry.pager_counters.inc(
                "fault_timeout" if late else "fault_ok")
            self._cond.notify_all()
        waited = time.perf_counter() - t0
        _slog.info("pager_fault_in", model=entry.name,
                   build_ms=round(build_s * 1e3, 3),
                   waited_ms=round(waited * 1e3, 3),
                   host_bytes=recipe.host_bytes)
        if late:
            # the model IS resident now (the work is not wasted), but
            # THIS request missed its cold-start SLO
            raise ColdStartTimeout(
                f"model {entry.name!r} faulted in, but past this "
                "request's deadline", model=entry.name, state=RESIDENT,
                waited_ms=round(waited * 1e3, 3))
        return waited

    # ---- eviction ------------------------------------------------------
    @staticmethod
    def _inflight(dep) -> int:
        """Requests that passed the residency check and have not yet
        completed, from the per-deployment counters the request path
        already maintains (no extra lock on the hot path)."""
        c = dep.counters.snapshot()
        return (c.get("started", 0) - c.get("aborted", 0)
                - c.get("requests", 0) - c.get("errors", 0))

    def _wait_quiesce(self, dep) -> bool:
        end = time.monotonic() + self.quiesce_timeout_s
        while self._inflight(dep) > 0:
            if time.monotonic() > end:
                return False
            time.sleep(0.002)
        return True

    def _try_evict(self, name: str, entry, reason: str) -> bool:
        """Demote one resident entry to cold.  In-flight-safe: new
        arrivals divert to the fault path the moment the state leaves
        RESIDENT; the handle is closed only after the in-flight
        balance quiesces.  A model that stays busy past the quiesce
        bound is hot — residency is restored and the eviction reports
        False."""
        with self._cond:
            if entry.pager_state != RESIDENT:
                return False
            entry.pager_state = EVICTING
            gen = entry.pager_gen
        dep = entry.active
        if dep is None or not self._wait_quiesce(dep):
            with self._cond:
                if entry.pager_gen == gen and \
                        entry.pager_state == EVICTING:
                    entry.pager_state = RESIDENT
                self._cond.notify_all()
            return False
        model = None
        with entry.lock:
            if entry.active is dep:
                model, dep.model = dep.model, None
        if model is not None:
            model.close()
        with self._cond:
            if entry.pager_gen == gen and entry.pager_state == EVICTING:
                entry.pager_state = COLD
            entry.pager_counters.inc("evict_" + reason)
            self._cond.notify_all()
        _slog.info("pager_evict", model=name, reason=reason)
        return True

    def _evict_for_budget(self, exclude=None):
        """Make room for one incoming resident entry: evict LRU
        resident entries (never ``exclude`` — the one faulting in)
        until the occupied count fits the budget.  Best-effort: a
        victim that will not quiesce is skipped, transient overcommit
        by in-flight faults is tolerated (the budget is a working-set
        target, not a hard device-memory wall)."""
        while True:
            with self._cond:
                occupied = [(e.pager_stamp, n, e)
                            for n, e in self._entries.items()
                            if e.pager_state in (RESIDENT, FAULTING,
                                                 EVICTING)]
                # occupied already counts the incoming entry (RESIDENT
                # from note_swapped, FAULTING from a fault) — evict
                # only when it would EXCEED the budget, or a budget of
                # N silently serves N-1 resident models
                if len(occupied) <= self.max_resident:
                    return
                victims = sorted(
                    (t, n, e) for t, n, e in occupied
                    if e is not exclude and e.pager_state == RESIDENT)
            if not victims:
                return
            evicted = False
            for _, vname, ventry in victims:
                if self._try_evict(vname, ventry, "pressure"):
                    evicted = True
                    break
            if not evicted:
                return

    # ---- idle reaper ---------------------------------------------------
    def start_reaper(self):
        """Start the idle-eviction thread (no-op unless
        ``idle_evict_s`` is configured; idempotent)."""
        if self.idle_evict_s is None or self._closed:
            return
        if self._reaper is not None and self._reaper.is_alive():
            return
        t = threading.Thread(target=self._reap_loop,
                             name="zoo-pager-reaper", daemon=True)
        self._reaper = t
        t.start()

    def _reap_loop(self):
        while not self._stop.wait(self._reap_interval_s):
            now = time.monotonic()
            with self._cond:
                idle = [(n, e) for n, e in self._entries.items()
                        if e.pager_state == RESIDENT
                        and now - e.pager_stamp >= self.idle_evict_s]
            for n, e in idle:
                if self._stop.is_set():
                    return
                self._try_evict(n, e, "idle")
