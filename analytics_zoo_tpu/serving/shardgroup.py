"""Sharded serving: replica GROUPS over device sub-meshes.

Every serving layer below this module equates "replica" with "one
device", so a model exceeding one chip's HBM cannot serve at all.  A
:class:`ShardGroupSet` generalizes the ``ReplicaSet`` contract to M
replica *groups*: each group is a pjit executable over a sub-mesh
carved from the local device set, the model's weight tree sharded
across the group's devices by a declarative rule table
(:mod:`analytics_zoo_tpu.parallel.sharding`).

Compile-once / place-everywhere survives the generalization intact —
that is the point of building this on the ``ReplicaSet`` hooks rather
than beside them.  The sharded forward is lowered and compiled ONCE
per padded signature (on group 0's sub-mesh), the executable is
serialized to the persistent store, and every other group rehydrates
the same bytes with only the :class:`DeviceAssignment` rewritten to
span the group's devices — a ``(1, group_size)`` assignment (one
replica, ``group_size`` partitions) instead of the single-device
``(1, 1)``.  A second group, a second process, and a pager fault-in
all instantiate with ZERO compile events.

Scheduling, health probing, elasticity, and in-flight accounting are
inherited: the coalescer's least-outstanding-work scheduler picks
among *groups* exactly as it picked among devices, because a group IS
a replica to every caller (``ShardGroup`` subclasses ``Replica``;
``group.device`` is the group's first device for anything that wants
one device, e.g. log labels).

Bit-exactness: with the default (and recommended) column rules —
every matched weight sharded along its LAST axis — XLA partitions the
forward as all-gather + full local contraction, which performs the
identical float operations in the identical order as the unsharded
program, so 1-group-of-N output is bit-identical to single-device
(``bench.py sharded`` gates this).  Contraction-dim (row) sharding
instead lowers to partial-dot + psum, whose float add order differs:
supported, but NOT bit-exact — choose it for memory, not for the
oracle.

The mesh spec (``normalize_mesh_spec``) is a small JSON-safe dict so
it rides the deploy envelope end to end: ``InferenceModel(mesh=...)``,
``ModelRegistry.deploy(..., mesh=...)``, the pager's rebuild recipe,
and the fleet artifact's ``mesh`` section all build the identical
sharded executable from the identical spec — and the spec's canonical
form is folded into the execstore fingerprint, so two deploys
differing only in mesh shape or partition rules can never serve each
other's entries.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.lib import xla_client as _xla_client

from ..observability import profile as _profile
from ..observability.log import get_logger as _get_logger
from ..parallel.mesh import AXES as _MESH_AXES
from ..parallel.sharding import (fsdp_tree, replicated_tree,
                                 tensor_parallel_tree)
from ..pipeline.inference.serving import Replica, ReplicaSet

_slog = _get_logger("zoo.shardgroup")

_STRATEGIES = ("tp", "tensor", "fsdp", "replicate")


def normalize_mesh_spec(spec) -> Dict[str, Any]:
    """Validate and canonicalize a deploy-spec ``mesh`` section.

    Accepted keys::

        axes:          {axis_name: size} — the sub-mesh each group
                       spans; group size = product of sizes.  Axis
                       names must come from parallel.mesh.AXES.
        groups:        "all" (default) — as many groups as the device
                       set holds — or an explicit int >= 1.
        strategy:      "tp" (default) | "tensor" | "fsdp" | "replicate"
        rules:         {param-path regex: axis index} for tp — when
                       omitted, the default column rules shard every
                       >=2-D weight's LAST axis (bit-exact, see module
                       docstring).
        fsdp_min_size: replicate params smaller than this (fsdp only).

    Returns a plain-dict canonical form (sorted keys via
    :func:`mesh_spec_canonical`) that is BOTH the build input and the
    fingerprint component — there is no second interpretation to
    drift."""
    if not isinstance(spec, dict):
        raise ValueError(f"mesh spec must be a dict, got {type(spec).__name__}")
    unknown = set(spec) - {"axes", "groups", "strategy", "rules",
                           "fsdp_min_size"}
    if unknown:
        raise ValueError(f"unknown mesh spec keys: {sorted(unknown)}")
    axes_in = spec.get("axes") or {"tensor": 1}
    if not isinstance(axes_in, dict) or not axes_in:
        raise ValueError("mesh spec 'axes' must be a non-empty dict")
    axes: Dict[str, int] = {}
    for name, size in axes_in.items():
        if name not in _MESH_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} (choose from {_MESH_AXES})")
        size = int(size)
        if size < 1:
            raise ValueError(f"mesh axis {name!r} size must be >= 1")
        axes[name] = size
    groups = spec.get("groups", "all")
    if groups != "all":
        groups = int(groups)
        if groups < 1:
            raise ValueError("mesh spec 'groups' must be >= 1 or 'all'")
    strategy = spec.get("strategy", "tp")
    if strategy not in _STRATEGIES:
        raise ValueError(f"unknown sharding strategy {strategy!r} "
                         f"(choose from {_STRATEGIES})")
    rules = spec.get("rules") or None
    if rules is not None:
        if not isinstance(rules, dict):
            raise ValueError("mesh spec 'rules' must map regex -> axis index")
        rules = {str(k): int(v) for k, v in rules.items()}
    return {"axes": axes, "groups": groups, "strategy": strategy,
            "rules": rules,
            "fsdp_min_size": int(spec.get("fsdp_min_size", 2 ** 14))}


def group_size(spec: Dict[str, Any]) -> int:
    """Devices per group: the product of the spec's axis sizes."""
    n = 1
    for s in spec["axes"].values():
        n *= int(s)
    return n


def mesh_spec_canonical(spec: Dict[str, Any]) -> str:
    """The spec's canonical JSON — the execstore fingerprint component
    (sorted keys, no whitespace variance) AND the ``--by-mesh`` meta."""
    return json.dumps(spec, sort_keys=True, separators=(",", ":"))


def carve_groups(devices, spec: Dict[str, Any]
                 ) -> List[Tuple[Tuple, Mesh]]:
    """Carve ``devices`` into replica groups: consecutive runs of
    ``group_size`` devices, each wrapped in a Mesh shaped by the
    spec's axes.  Leftover devices (count not divisible) stay idle —
    logged, never silently half-grouped."""
    devs = list(devices)
    gsize = group_size(spec)
    if gsize > len(devs):
        raise ValueError(
            f"mesh spec needs {gsize} devices per group but only "
            f"{len(devs)} are available")
    n_groups = len(devs) // gsize
    if spec["groups"] != "all":
        if spec["groups"] > n_groups:
            raise ValueError(
                f"mesh spec asks for {spec['groups']} groups of "
                f"{gsize} but only {len(devs)} devices are available")
        n_groups = spec["groups"]
    leftover = len(devs) - n_groups * gsize
    if leftover and spec["groups"] == "all":
        _slog.info("shardgroup_devices_idle", idle=leftover,
                   group_size=gsize, groups=n_groups)
    names = tuple(spec["axes"])
    shape = tuple(spec["axes"][n] for n in names)
    out = []
    for g in range(n_groups):
        gdevs = tuple(devs[g * gsize:(g + 1) * gsize])
        out.append((gdevs, Mesh(np.asarray(gdevs).reshape(shape), names)))
    return out


def _column_tree(params, mesh: Mesh, axis: str = "tensor"):
    """The default rule table: shard every >=2-D param along its LAST
    axis when divisible by the tensor-axis size, replicate the rest.
    Last-axis (column) splits keep the partitioned program gather-only
    — the bit-exact layout (module docstring)."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return replicated_tree(params, mesh)
    n = mesh.shape[axis]

    def rule(p):
        shape = np.shape(p)
        if len(shape) >= 2 and shape[-1] % n == 0:
            spec = [None] * len(shape)
            spec[-1] = axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def spec_tree_for(params, mesh: Mesh, spec: Dict[str, Any]):
    """Resolve the spec's strategy + rule table into a NamedSharding
    tree for ``params`` on ``mesh``."""
    strategy = spec["strategy"]
    if strategy == "replicate":
        return replicated_tree(params, mesh)
    if strategy == "fsdp":
        return fsdp_tree(params, mesh, axis="fsdp",
                         min_size=spec["fsdp_min_size"])
    # tp / tensor
    if spec["rules"]:
        return tensor_parallel_tree(params, mesh, spec["rules"])
    return _column_tree(params, mesh)


class ShardGroup(Replica):
    """One replica group: a tuple of devices, the Mesh spanning them,
    and the group's sharded copy of the params.  IS-A ``Replica`` so
    the scheduler, health probing, elasticity, and per-replica
    counters apply unchanged — ``device`` is the group's first device
    for anything that wants a single device (log labels, backend
    access)."""

    __slots__ = ("devices", "mesh", "in_sharding")

    def __init__(self, index: int, devices: Tuple, mesh: Mesh,
                 params_flat: List):
        super().__init__(index, devices[0], params_flat)
        self.devices = tuple(devices)
        self.mesh = mesh
        # batch inputs are replicated across the group: every device
        # holds the full padded batch, the weights carry the sharding
        self.in_sharding = NamedSharding(mesh, P())

    def __repr__(self):
        return (f"ShardGroup({self.index}, {len(self.devices)} devices, "
                f"healthy={self.healthy}, active={self.active})")


class ShardGroupSet(ReplicaSet):
    """M replica groups over device sub-meshes — the ``ReplicaSet``
    contract with "device" generalized to "group" (see module
    docstring for the full design).  Constructed with a normalized
    mesh spec; everything else (store protocol, scheduler, health,
    elasticity) is inherited behavior."""

    def __init__(self, fn, params, mesh_spec, devices=None, **kw):
        self._mesh_spec = normalize_mesh_spec(mesh_spec)
        self._spec_canonical = mesh_spec_canonical(self._mesh_spec)
        super().__init__(fn, params, devices=devices, **kw)

    # ---- placement-unit hooks ----
    def _carve_units(self, devices) -> List:
        devs = list(devices) if devices else list(jax.local_devices())
        if not devs:
            raise ValueError("ShardGroupSet needs at least one device")
        return carve_groups(devs, self._mesh_spec)

    @staticmethod
    def _unit_devices(unit) -> Tuple:
        return unit[0]

    def _make_jit(self, units):
        # outputs replicate across the group — serving returns whole
        # batches to the host, and a replicated output disassembles
        # into identical per-device shards (dispatch() takes shard 0)
        _, mesh0 = units[0]
        return jax.jit(self._fn,
                       out_shardings=NamedSharding(mesh0, P()))

    def _place_params(self, params, unit):
        gdevs, mesh = unit
        return jax.device_put(
            params, spec_tree_for(params, mesh, self._mesh_spec))

    def _make_replica(self, index: int, unit, placed) -> ShardGroup:
        gdevs, mesh = unit
        return ShardGroup(index, gdevs, mesh,
                          jax.tree_util.tree_leaves(placed))

    def _input_sharding(self):
        return self.groups[0].in_sharding

    def _fp_parts(self) -> Tuple:
        # the canonical mesh spec rotates the store key whenever the
        # mesh shape, group layout, or partition rules change — the
        # PR 14 discipline (sampling config in the fingerprint),
        # applied to layout
        return ("shardgroup-forward", self._spec_canonical)

    def _store_meta(self) -> Dict[str, Any]:
        return {"kind": "shardgroup-forward",
                "mesh": {"axes": dict(self._mesh_spec["axes"]),
                         "strategy": self._mesh_spec["strategy"],
                         "group_size": self.group_size}}

    def span_labels(self, replica) -> Dict[str, Any]:
        # a "replica" here IS a group — label both so dashboards keyed
        # on either name resolve, and traces show which group served
        return {"replica": replica.index, "group": replica.index}

    def _place_serialized(self, ser: bytes, group: ShardGroup):
        """Rehydrate onto one GROUP: a ``(1, group_size)`` device
        assignment — one replica, ``group_size`` partitions spanning
        the group's devices — instead of the base class's ``(1, 1)``.
        Still a load, never a compile: zero ``backend_compile`` events
        (the bench's ``SHARDED_ZERO_COMPILE`` gate counts)."""
        opts = _xla_client.CompileOptions()
        opts.device_assignment = _xla_client.DeviceAssignment.create(
            np.array([[d.id for d in group.devices]], dtype=np.int32))
        return self._backend.deserialize_executable(ser, opts)

    # ---- identity / introspection ----
    @property
    def groups(self) -> Tuple[ShardGroup, ...]:
        return self.replicas

    @property
    def group_size(self) -> int:
        return len(self.replicas[0].devices)

    @property
    def mesh_spec(self) -> Dict[str, Any]:
        return self._mesh_spec

    # ---- dispatch ----
    def dispatch(self, replica: ShardGroup, batched, spans=(),
                 key: Optional[Tuple] = None):
        """Upload one exactly-bucket-sized host batch to the group
        (replicated across its devices) and run the group's sharded
        executable; returns the DEVICE result tree (fetch via
        :func:`fetch_rows`).  Mirrors ``ReplicaSet.dispatch`` with the
        raw single-device ``execute`` swapped for ``execute_sharded``
        + shard reassembly — outputs are replicated (``_make_jit``
        pins ``out_shardings``), so each output is rebuilt from its
        per-device shards with the group's mesh."""
        if key is None:
            key = self._key(batched)
        exe = self._exes[key][replica.index]
        for s in spans:
            s.phase_start("device_put")
        in_sh = replica.in_sharding
        dev_x = [jax.device_put(a, in_sh)
                 for a in jax.tree_util.tree_leaves(batched)]
        _profile.note_transfer("h2d")
        args = replica.params_flat + dev_x
        kept = self._kept[key]
        if kept is not None:
            args = [args[i] for i in kept]
        for s in spans:
            s.phase_start("execute")
        results = exe.execute_sharded(args)
        shards_per_out = results.disassemble_into_single_device_arrays()
        out_sh = NamedSharding(replica.mesh, P())
        outs = [jax.make_array_from_single_device_arrays(
                    av.shape, out_sh, shards)
                for av, shards in zip(self._out_avals[key],
                                      shards_per_out)]
        return jax.tree_util.tree_unflatten(self._out_tree[key], outs)

    # ---- stats ----
    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out.update({
            "groups": len(self.replicas),
            "group_size": self.group_size,
            "group_dispatches": {g.index: g.dispatches
                                 for g in self.replicas},
            "mesh_axes": dict(self._mesh_spec["axes"]),
        })
        return out
