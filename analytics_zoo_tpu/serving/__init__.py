"""Serving control plane: multi-model registry, zero-downtime hot-swap,
admission control & priority-aware load shedding, canary traffic
splitting, replica autoscaling, and a metrics snapshot API — the
lifecycle layer over the ``pipeline.inference`` data plane (bucketed
executables + request coalescing + replica sets).  See docs/serving.md
§"Control plane" and §"Elasticity"."""

from . import execstore, fleet
from .admission import AdmissionController
from .autoscale import Autoscaler, autoscaler_for
from .errors import (DeadlineExceeded, DeployError, ModelNotFound,
                     Overloaded, ServingError, error_response)
from .execstore import ExecStore
from .metrics import (Counters, LatencyWindow, registry_collector,
                      registry_families)
from .registry import ModelRegistry

__all__ = [
    "AdmissionController", "Autoscaler", "Counters", "DeadlineExceeded",
    "DeployError", "ExecStore", "LatencyWindow", "ModelNotFound",
    "ModelRegistry", "Overloaded", "ServingError", "autoscaler_for",
    "error_response", "execstore", "fleet", "registry_collector",
    "registry_families",
]
