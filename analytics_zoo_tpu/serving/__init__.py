"""Serving control plane: multi-model registry, zero-downtime hot-swap,
admission control & load shedding, canary traffic splitting, and a
metrics snapshot API — the lifecycle layer over the
``pipeline.inference`` data plane (bucketed executables + request
coalescing).  See docs/serving.md §"Control plane"."""

from .admission import AdmissionController
from .errors import (DeadlineExceeded, DeployError, ModelNotFound,
                     Overloaded, ServingError, error_response)
from .metrics import (Counters, LatencyWindow, registry_collector,
                      registry_families)
from .registry import ModelRegistry

__all__ = [
    "AdmissionController", "Counters", "DeadlineExceeded", "DeployError",
    "LatencyWindow", "ModelNotFound", "ModelRegistry", "Overloaded",
    "ServingError", "error_response", "registry_collector",
    "registry_families",
]
