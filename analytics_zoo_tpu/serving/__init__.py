"""Serving control plane: multi-model registry, zero-downtime hot-swap,
admission control & priority-aware load shedding, canary traffic
splitting, replica autoscaling, weight/executable paging for serving
density, and a metrics snapshot API — the lifecycle layer over the
``pipeline.inference`` data plane (bucketed executables + request
coalescing + replica sets).  See docs/serving.md §"Control plane",
§"Elasticity" and §"Serving density & weight paging"."""

from . import execstore, fleet
from .admission import AdmissionController
from .autoscale import Autoscaler, autoscaler_for
from .errors import (ColdStartTimeout, DeadlineExceeded, DeployError,
                     ModelNotFound, Overloaded, ServingError,
                     error_response)
from .execstore import ExecStore
from .metrics import (Counters, LatencyWindow, registry_collector,
                      registry_families)
from .pager import ModelPager, PageRecipe
from .registry import ModelRegistry
from .shardgroup import (ShardGroup, ShardGroupSet, carve_groups,
                         normalize_mesh_spec)

__all__ = [
    "AdmissionController", "Autoscaler", "ColdStartTimeout", "Counters",
    "DeadlineExceeded", "DeployError", "ExecStore", "LatencyWindow",
    "ModelNotFound", "ModelPager", "ModelRegistry", "Overloaded",
    "PageRecipe", "ServingError", "ShardGroup", "ShardGroupSet",
    "autoscaler_for", "carve_groups", "error_response", "execstore",
    "fleet", "normalize_mesh_spec", "registry_collector",
    "registry_families",
]
