"""Replica autoscaling: the control loop from serving signals to
serving capacity.

PR 5 built the multi-replica data plane and PR 2/4 built the signals
(admission queue depth, EWMA service time, per-phase spans); until now
every capacity knob was frozen at deploy time.  The
:class:`Autoscaler` closes the loop: a small periodic controller reads
the admission signals and resizes the live ``ReplicaSet``'s ACTIVE set
— reusing the registry's warm-before-activate discipline at runtime
(``ReplicaSet.set_active`` primes every placed executable on a joining
replica before it takes traffic), so a scale-up never serves a cold
replica and never compiles — and re-bounds the model's
``AdmissionController`` to ``base_concurrency * active_replicas`` on
every transition.

Stability over reactivity, by construction:

* **hysteresis** — a scale signal must hold for ``hold_ticks``
  consecutive control intervals before it acts; a single queue blip
  scales nothing;
* **cooldown** — after any transition, no further transition for
  ``cooldown_s`` (the "≤ 1 transition per cooldown window" flapping
  bound the loadtest gate checks);
* **one step at a time** — transitions move the active count by ±1, so
  an overshooting spike cannot slam capacity to max and back.

The decision core is deliberately side-effect free apart from the two
injected callables (``get_signals`` / ``apply_scale``), so tests drive
``tick()`` directly with synthetic signals and a fake clock — no
threads, no sleeping.  ``autoscaler_for(registry, name)`` wires the
real thing: signals from the model's admission snapshot, scaling onto
the active deployment (re-resolved every call, so a hot-swap mid-flight
lands on the NEW model's replica set).

Usage::

    scaler = autoscaler_for(registry, "default", min_replicas=1,
                            up_queue_depth=8, cooldown_s=5.0)
    scaler.start()           # daemon control thread
    ...
    scaler.stop()
    scaler.events()          # the scale-event timeline
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..observability.log import get_logger as _get_logger
from ..observability.metrics import Family
from .metrics import Counters

_slog = _get_logger("zoo.autoscale")


class Autoscaler:
    """Queue-depth / EWMA-latency driven replica controller (module
    docstring).

    ``get_signals()`` returns ``{"queue_depth": int, "ewma_ms":
    float|None, "active": int|None}`` plus optional ``running`` /
    ``max_concurrency`` (scale-down additionally requires a free
    concurrency slot when both are present — an empty queue under
    full-slot saturation is load, not idleness).  ``active`` (when
    present) re-syncs the controller's view of the live replica
    count, so an external change (hot-swap deploying a fresh
    all-active set) is observed rather than fought.  ``apply_scale(n)`` makes ``n``
    replicas live; it must be synchronous (the warm-prime happens
    inside it) and may raise — a failed transition is logged, counted,
    and retried after the cooldown.
    """

    def __init__(self, get_signals: Callable[[], Dict[str, Any]],
                 apply_scale: Callable[[int], Any], *,
                 min_replicas: int = 1, max_replicas: int,
                 initial_replicas: Optional[int] = None,
                 up_queue_depth: float = 8.0,
                 down_queue_depth: float = 1.0,
                 up_latency_ms: Optional[float] = None,
                 down_latency_ms: Optional[float] = None,
                 hold_ticks: int = 2, cooldown_s: float = 5.0,
                 interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "model"):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})")
        self.get_signals = get_signals
        self.apply_scale = apply_scale
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_depth = float(up_queue_depth)
        self.down_queue_depth = float(down_queue_depth)
        self.up_latency_ms = up_latency_ms
        self.down_latency_ms = down_latency_ms
        self.hold_ticks = max(1, int(hold_ticks))
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.name = name
        self._clock = clock
        self.n_active = int(initial_replicas
                            if initial_replicas is not None
                            else max_replicas)
        self._up_streak = 0
        self._down_streak = 0
        # cooldown starts satisfied: the first held signal may act
        self._last_transition = clock() - self.cooldown_s
        # bounded timeline: a standing server transitioning once per
        # cooldown forever must not grow memory (totals live in the
        # counters; the ring keeps the recent history scrapes read)
        self._events: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=512)
        self.counters = Counters("ticks", "scale_up", "scale_down",
                                 "apply_errors")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- the control step ----
    def tick(self) -> Optional[Dict[str, Any]]:
        """One control interval: read signals, update streaks, maybe
        transition.  Returns the scale event dict when one happened,
        else None.  Deterministic given signals + clock — the tests'
        entry point, and the only place state changes."""
        self.counters.inc("ticks")
        sig = self.get_signals()
        if sig.get("active"):
            # observed truth wins over our bookkeeping (a hot-swap just
            # deployed a fresh, fully-active replica set)
            self.n_active = int(sig["active"])
        depth = float(sig.get("queue_depth") or 0)
        ewma = sig.get("ewma_ms")
        running = sig.get("running")
        cap = sig.get("max_concurrency")
        # an empty queue is NOT idleness when every concurrency slot
        # is busy: a closed-loop saturator keeps depth at 0 while the
        # model runs flat out, and scaling down under 100% utilization
        # just starts a perpetual down/up oscillation (signals without
        # the keys — synthetic tests — place no constraint)
        has_free_slots = (running is None or cap is None
                          or running < cap)
        want_up = depth >= self.up_queue_depth or (
            self.up_latency_ms is not None and ewma is not None
            and ewma >= self.up_latency_ms)
        want_down = depth <= self.down_queue_depth \
            and has_free_slots and (
                self.down_latency_ms is None or ewma is None
                or ewma <= self.down_latency_ms)
        self._up_streak = self._up_streak + 1 if want_up else 0
        self._down_streak = self._down_streak + 1 if want_down else 0
        now = self._clock()
        if now - self._last_transition < self.cooldown_s:
            return None  # ≤1 transition per cooldown window, by law
        target = self.n_active
        direction = None
        if self._up_streak >= self.hold_ticks \
                and self.n_active < self.max_replicas:
            target, direction = self.n_active + 1, "up"
        elif self._down_streak >= self.hold_ticks \
                and self.n_active > self.min_replicas:
            target, direction = self.n_active - 1, "down"
        if direction is None:
            return None
        try:
            self.apply_scale(target)
        except Exception as e:  # noqa: BLE001 — the loop must survive
            self.counters.inc("apply_errors")
            _slog.error("autoscale_apply_failed", model=self.name,
                        target=target,
                        error=f"{type(e).__name__}: {e}")
            # back off a full cooldown before retrying the transition
            self._last_transition = now
            return None
        event = {"t": now, "direction": direction,
                 "from_replicas": self.n_active,
                 "to_replicas": target,
                 "queue_depth": depth, "ewma_ms": ewma}
        self.n_active = target
        self._last_transition = now
        self._up_streak = self._down_streak = 0
        self.counters.inc(f"scale_{direction}")
        self._events.append(event)
        _slog.info("autoscale", model=self.name, **{
            k: v for k, v in event.items() if k != "t"})
        return event

    # ---- background loop ----
    def start(self):
        """Run ``tick()`` every ``interval_s`` on a daemon thread
        (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="zoo-autoscale", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — keep controlling
                _slog.error("autoscale_tick_failed", model=self.name,
                            error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.interval_s)

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # ---- read side ----
    def events(self) -> List[Dict[str, Any]]:
        """The scale-event timeline so far (oldest first)."""
        return list(self._events)

    def snapshot(self) -> Dict[str, Any]:
        return {"active_replicas": self.n_active,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "events": self.events(),
                **self.counters.snapshot()}

    def families(self) -> List[Family]:
        """Prometheus collector (plug into a MetricsRegistry):
        ``zoo_autoscale_events_total{model,direction}`` plus the
        active/min/max replica gauges."""
        c = self.counters.snapshot()
        ml = {"model": self.name}
        return [
            Family("counter", "zoo_autoscale_events_total",
                   "replica scale transitions",
                   [({**ml, "direction": "up"}, c["scale_up"]),
                    ({**ml, "direction": "down"}, c["scale_down"])]),
            Family("gauge", "zoo_autoscale_active_replicas",
                   "replicas currently in the scheduled set",
                   [(ml, self.n_active)]),
            Family("gauge", "zoo_autoscale_max_replicas",
                   "autoscaler replica ceiling",
                   [(ml, self.max_replicas)]),
        ]


def autoscaler_for(registry, name: str, **kwargs: Any) -> Autoscaler:
    """An :class:`Autoscaler` wired to one registry model: signals from
    its admission snapshot (+ the live active-replica count, so a
    hot-swap re-syncs the controller), scaling onto the ACTIVE
    deployment's replica set, and the admission concurrency re-bounded
    to ``base * n`` on every transition — the runtime generalization of
    the deploy-time rescale.  ``max_replicas`` defaults to the active
    model's total replica count."""
    entry = registry._entry(name)
    base = registry._max_concurrency

    def _model():
        dep = entry.active
        if dep is None:
            raise RuntimeError(
                f"model {name!r} has no active version to scale")
        return dep.model

    def get_signals() -> Dict[str, Any]:
        snap = entry.admission.snapshot()
        # single read: a concurrent undeploy nulls entry.active, and a
        # check-then-dereference would crash every tick thereafter
        dep = entry.active
        m = dep.model if dep is not None else None
        return {"queue_depth": snap["queue_depth"],
                "ewma_ms": snap["service_ewma_ms"],
                "running": snap["running"],
                "max_concurrency": snap["max_concurrency"],
                "active": (getattr(m, "active_replicas", None)
                           if m is not None else None)}

    def apply_scale(n: int):
        got = _model().set_active_replicas(n)
        entry.admission.set_max_concurrency(base * max(1, got))

    model = _model()
    total = getattr(model, "n_replicas", 1) or 1
    kwargs.setdefault("max_replicas", total)
    kwargs.setdefault("initial_replicas",
                      getattr(model, "active_replicas", total))
    return Autoscaler(get_signals, apply_scale, name=name, **kwargs)
