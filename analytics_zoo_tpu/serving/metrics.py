"""Serving metrics: primitives re-homed to
``analytics_zoo_tpu.observability.metrics`` (imported back here so
every existing ``serving.metrics`` / ``serving.Counters`` consumer
keeps working), plus the control-plane -> Prometheus bridge.

The bridge is a scrape-time collector: it walks one
``ModelRegistry.metrics()`` snapshot into exposition families with
per-model / per-version / per-bucket labels, so wiring the whole
control plane into a :class:`~..observability.metrics.MetricsRegistry`
is one line::

    mreg.register_collector(registry_collector(model_registry))
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

from ..observability.metrics import (Counters, Family, LatencyWindow,
                                     summary_family)

__all__ = ["Counters", "LatencyWindow", "registry_collector",
           "registry_families"]

_ADMISSION_GAUGES = ("queue_depth", "running", "queue_high_water",
                     "max_queue", "max_concurrency")
_ADMISSION_COUNTERS = ("admitted", "completed", "errors",
                       "shed_overload", "shed_deadline",
                       "shed_draining", "shed_evicted",
                       "deadline_lapsed")
_HEDGE_OUTCOMES = ("fired", "primary_won", "hedge_won",
                   "skipped_no_replica")


def registry_families(snapshot: Dict[str, Any]) -> List[Family]:
    """One ``ModelRegistry.metrics()`` snapshot as Prometheus families
    (per-model/version/bucket labels — see module docstring)."""
    model_gauges: Dict[str, List] = {
        "zoo_model_active_version": [],
        "zoo_model_canary_fraction": [],
        "zoo_coalescer_pending": [],
    }
    model_counters: Dict[str, List] = {"zoo_model_swap_total": []}
    admission: Dict[str, List] = {
        **{f"zoo_admission_{g}": [] for g in _ADMISSION_GAUGES},
        **{f"zoo_admission_{c}_total": [] for c in _ADMISSION_COUNTERS},
    }
    version_counters: Dict[str, List] = {
        "zoo_model_requests_total": [],
        "zoo_model_errors_total": [],
    }
    version_gauges: Dict[str, List] = {"zoo_model_uptime_seconds": [],
                                       "zoo_model_version_state": []}
    bucket_counters: Dict[str, List] = {
        "zoo_bucket_hits_total": [],
        "zoo_bucket_misses_total": [],
        "zoo_bucket_compile_seconds_total": [],
    }
    replica_counters: Dict[str, List] = {
        "zoo_replica_dispatches_total": [],
        "zoo_replica_bucket_dispatches_total": [],
        "zoo_group_dispatches_total": [],
    }
    replica_gauges: Dict[str, List] = {
        "zoo_replica_unhealthy": [],
        "zoo_model_replicas": [],
        "zoo_model_replicas_active": [],
        "zoo_model_groups": [],
    }
    # elastic serving: per-class admission + hedge outcomes
    class_counters: Dict[str, List] = {
        "zoo_shed_total": [],
        "zoo_class_admitted_total": [],
        "zoo_hedge_total": [],
    }
    class_gauges: Dict[str, List] = {"zoo_class_weight": []}
    coalescer_counters: Dict[str, List] = {
        "zoo_coalescer_dispatches_total": [],
        "zoo_coalesced_requests_total": [],
    }
    # continuous-batching decode: per-token/step counters + the live
    # slot-occupancy gauge (capacity alongside, so occupancy reads as
    # a fraction without a dashboard join).  Decode engine v2 adds the
    # sampled-token counter, the prefix-pool hit/miss pair (their
    # ratio is the shared-prefix win), and the speculative
    # proposed/accepted pair (their ratio is the acceptance rate the
    # spec bench gates on) — exported whenever a decode engine is
    # live, zeros until the feature serves traffic, so dashboards and
    # alerts can pre-wire at deploy
    decode_counters: Dict[str, List] = {
        "zoo_decode_tokens_total": [],
        "zoo_decode_steps_total": [],
        "zoo_decode_sampled_tokens_total": [],
        "zoo_decode_prefix_hits_total": [],
        "zoo_decode_prefix_misses_total": [],
        "zoo_decode_spec_proposed_total": [],
        "zoo_decode_spec_accepted_total": [],
    }
    decode_gauges: Dict[str, List] = {
        "zoo_decode_slot_occupancy": [],
        "zoo_decode_slot_capacity": [],
    }
    # weight pager (serving density): residency per model plus the
    # fault/eviction outcome counters — exported for every PAGED model
    # (zeros until the pager acts) so density dashboards pre-wire
    pager_gauges: Dict[str, List] = {"zoo_model_resident": []}
    pager_counters: Dict[str, List] = {
        "zoo_pager_faults_total": [],
        "zoo_pager_evictions_total": [],
    }
    # ONE summary family for every (model, version): emitting a Family
    # per version would render duplicate # TYPE blocks for the same
    # name, which real Prometheus parsers reject outright
    latency_samples: List = []

    for model, m in sorted(snapshot.items()):
        ml = {"model": model}
        if m.get("active_version") is not None:
            model_gauges["zoo_model_active_version"].append(
                (ml, m["active_version"]))
        model_counters["zoo_model_swap_total"].append(
            (ml, m.get("swap_count", 0)))
        model_gauges["zoo_model_canary_fraction"].append(
            (ml, m.get("canary_fraction", 0.0)))
        adm = m.get("admission", {})
        for g in _ADMISSION_GAUGES:
            if g in adm:
                admission[f"zoo_admission_{g}"].append((ml, adm[g]))
        for c in _ADMISSION_COUNTERS:
            if c in adm:
                admission[f"zoo_admission_{c}_total"].append(
                    (ml, adm[c]))
        # per-priority-class admission: the shed counter is the
        # overload-ordering contract ("lowest class sheds first") made
        # observable, labeled by class; classes export even at zero so
        # dashboards/alerts can pre-wire on deploy
        for cname, cstats in sorted(adm.get("classes", {}).items()):
            cl = {"model": model, "class": cname}
            class_counters["zoo_shed_total"].append(
                (cl, cstats.get("shed", 0)))
            class_counters["zoo_class_admitted_total"].append(
                (cl, cstats.get("admitted", 0)))
            class_gauges["zoo_class_weight"].append(
                (cl, cstats.get("weight", 0.0)))
        for version, stats in sorted(m.get("versions", {}).items()):
            # counters/summaries carry ONLY immutable labels: adding
            # the mutable state would fork the series on every
            # canary promote / hot-swap and break rate() continuity
            # exactly at the event being monitored.  State rides a
            # separate info-style gauge instead.
            vl = {"model": model, "version": str(version)}
            version_counters["zoo_model_requests_total"].append(
                (vl, stats.get("requests", 0)))
            version_counters["zoo_model_errors_total"].append(
                (vl, stats.get("errors", 0)))
            version_gauges["zoo_model_version_state"].append(
                ({**vl, "state": str(stats.get("state", ""))}, 1))
            if stats.get("uptime_s") is not None:
                version_gauges["zoo_model_uptime_seconds"].append(
                    (vl, stats["uptime_s"]))
            lat = summary_family(
                "zoo_model_latency_seconds",
                "request latency over the sliding window",
                vl, stats.get("latency", {}))
            if lat is not None:
                latency_samples.extend(lat.samples)
        serving = m.get("serving", {})
        for prom_name, key in (("zoo_bucket_hits_total", "hits"),
                               ("zoo_bucket_misses_total", "misses"),
                               ("zoo_bucket_compile_seconds_total",
                                "compile_time_s")):
            for bucket, v in sorted(serving.get(key, {}).items()):
                bucket_counters[prom_name].append(
                    ({"model": model, "bucket": str(bucket)}, v))
        for prom_name, key in (
                ("zoo_coalescer_dispatches_total", "dispatches"),
                ("zoo_coalesced_requests_total", "coalesced_requests")):
            if key in serving:
                coalescer_counters[prom_name].append(
                    (ml, serving[key]))
        if "coalescer_pending" in serving:
            model_gauges["zoo_coalescer_pending"].append(
                (ml, serving["coalescer_pending"]))
        pager = m.get("pager")
        if pager:
            pager_gauges["zoo_model_resident"].append(
                (ml, 1 if pager.get("resident") else 0))
            for outcome, key in (("ok", "fault_ok"),
                                 ("timeout", "fault_timeout"),
                                 ("error", "fault_error")):
                pager_counters["zoo_pager_faults_total"].append(
                    ({"model": model, "outcome": outcome},
                     pager.get(key, 0)))
            for reason, key in (("idle", "evict_idle"),
                                ("pressure", "evict_pressure")):
                pager_counters["zoo_pager_evictions_total"].append(
                    ({"model": model, "reason": reason},
                     pager.get(key, 0)))
        dec = serving.get("decode")
        if dec:
            for prom_name, key in (
                    ("zoo_decode_tokens_total", "tokens"),
                    ("zoo_decode_steps_total", "steps"),
                    ("zoo_decode_sampled_tokens_total",
                     "sampled_tokens"),
                    ("zoo_decode_prefix_hits_total", "prefix_hits"),
                    ("zoo_decode_prefix_misses_total",
                     "prefix_misses"),
                    ("zoo_decode_spec_proposed_total",
                     "spec_proposed"),
                    ("zoo_decode_spec_accepted_total",
                     "spec_accepted")):
                decode_counters[prom_name].append(
                    (ml, dec.get(key, 0)))
            decode_gauges["zoo_decode_slot_occupancy"].append(
                (ml, dec.get("slots_active", 0)))
            decode_gauges["zoo_decode_slot_capacity"].append(
                (ml, dec.get("capacity", 0)))
        # device-parallel serving: per-replica dispatch counters (and
        # their per-bucket breakdown — the bucket metrics' replica
        # label) plus the health gauge
        # request hedging: outcome-labeled counters (fired /
        # primary_won / hedge_won / skipped_no_replica)
        for outcome in _HEDGE_OUTCOMES:
            v = serving.get("hedges", {}).get(outcome)
            if v is not None:
                class_counters["zoo_hedge_total"].append(
                    ({"model": model, "outcome": outcome}, v))
        if serving.get("replica_dispatches"):
            replica_gauges["zoo_model_replicas"].append(
                (ml, serving.get("replicas", 1)))
            if "replicas_active" in serving:
                replica_gauges["zoo_model_replicas_active"].append(
                    (ml, serving["replicas_active"]))
            for rep, v in sorted(serving["replica_dispatches"].items()):
                replica_counters["zoo_replica_dispatches_total"].append(
                    ({"model": model, "replica": str(rep)}, v))
            for rep, sick in sorted(
                    serving.get("replica_unhealthy", {}).items()):
                replica_gauges["zoo_replica_unhealthy"].append(
                    ({"model": model, "replica": str(rep)},
                     1 if sick else 0))
            for rep, per_bucket in sorted(
                    serving.get("replica_bucket_dispatches", {}).items()):
                for bucket, v in sorted(per_bucket.items()):
                    replica_counters[
                        "zoo_replica_bucket_dispatches_total"].append(
                        ({"model": model, "replica": str(rep),
                          "bucket": str(bucket)}, v))
        # sharded serving: replica GROUPS (pjit executables over
        # sub-meshes) export their own count + per-group dispatch
        # counters, keyed "group" so dashboards distinguish them from
        # single-device replicas
        if serving.get("groups"):
            replica_gauges["zoo_model_groups"].append(
                (ml, serving["groups"]))
            for grp, v in sorted(
                    serving.get("group_dispatches", {}).items()):
                replica_counters["zoo_group_dispatches_total"].append(
                    ({"model": model, "group": str(grp)}, v))

    help_text = {
        "zoo_model_active_version": "active (serving) version number",
        "zoo_model_swap_total": "completed hot-swaps",
        "zoo_model_canary_fraction":
            "fraction of traffic routed to the staged canary",
        "zoo_coalescer_pending":
            "submitted-but-unresolved coalesced requests",
        "zoo_model_requests_total": "served requests per version",
        "zoo_model_errors_total": "failed requests per version",
        "zoo_model_uptime_seconds":
            "seconds since this version deployed",
        "zoo_model_version_state":
            "info gauge: 1 for the version's current lifecycle state",
        "zoo_bucket_hits_total": "bucket executable cache hits",
        "zoo_bucket_misses_total":
            "bucket cache misses (compiles paid)",
        "zoo_bucket_compile_seconds_total":
            "compile wall seconds per bucket",
        "zoo_coalescer_dispatches_total": "coalesced device dispatches",
        "zoo_coalesced_requests_total":
            "requests served through coalesced dispatches",
        "zoo_model_replicas": "device replicas serving this model",
        "zoo_replica_dispatches_total":
            "device dispatches executed per replica",
        "zoo_replica_bucket_dispatches_total":
            "device dispatches per (replica, bucket)",
        "zoo_replica_unhealthy":
            "1 when the replica was marked unhealthy by a failed "
            "dispatch (restored to 0 by a successful health re-probe)",
        "zoo_model_replicas_active":
            "replicas in the scheduled (elastic) set",
        "zoo_model_groups":
            "sharded replica groups (pjit sub-mesh executables) "
            "serving this model",
        "zoo_group_dispatches_total":
            "device dispatches executed per replica group",
        "zoo_shed_total":
            "requests shed per priority class (all shed causes)",
        "zoo_class_admitted_total":
            "requests granted a slot per priority class",
        "zoo_class_weight": "configured fair-share weight per class",
        "zoo_hedge_total":
            "hedged dispatch outcomes (fired/primary_won/hedge_won/"
            "skipped_no_replica)",
        "zoo_decode_tokens_total":
            "tokens generated by the continuous-batching decode "
            "engine (prefill first tokens included)",
        "zoo_decode_steps_total":
            "slot-array decode steps dispatched",
        "zoo_decode_sampled_tokens_total":
            "tokens emitted by temperature > 0 (sampled) requests",
        "zoo_decode_prefix_hits_total":
            "admissions whose prefix-KV block was served from the "
            "on-device pool (prefill skipped for the prefix)",
        "zoo_decode_prefix_misses_total":
            "pool-eligible admissions that recomputed (and "
            "re-pooled) their prefix block",
        "zoo_decode_spec_proposed_total":
            "draft tokens proposed to the speculative verify step",
        "zoo_decode_spec_accepted_total":
            "draft proposals accepted by the target verify "
            "(accepted/proposed = acceptance rate)",
        "zoo_decode_slot_occupancy":
            "decode slots currently holding a live sequence",
        "zoo_decode_slot_capacity":
            "decode slots in the persistent step executable",
        "zoo_model_resident":
            "1 when the paged model's weights/executables are on-"
            "device (0 while cold/faulting/evicting)",
        "zoo_pager_faults_total":
            "cold-start fault-ins per paged model by request outcome "
            "(ok/timeout/error)",
        "zoo_pager_evictions_total":
            "pager demotions to cold per model by trigger "
            "(idle/pressure)",
    }
    out: List[Family] = []
    gauge_groups = (model_gauges, version_gauges, replica_gauges,
                    class_gauges, decode_gauges, pager_gauges,
                    {k: v for k, v in admission.items()
                     if not k.endswith("_total")})
    counter_groups = (model_counters, version_counters,
                      bucket_counters, coalescer_counters,
                      replica_counters, class_counters, decode_counters,
                      pager_counters,
                      {k: v for k, v in admission.items()
                       if k.endswith("_total")})
    for groups, mtype in ((gauge_groups, "gauge"),
                          (counter_groups, "counter")):
        for group in groups:
            for name, samples in group.items():
                if samples:
                    out.append(Family(
                        mtype, name,
                        help_text.get(name,
                                      name.replace("zoo_", "")
                                      .replace("_", " ")),
                        samples))
    if latency_samples:
        out.append(Family("summary", "zoo_model_latency_seconds",
                          "request latency over the sliding window",
                          latency_samples))
    return out


def registry_collector(model_registry) -> Callable[[], List[Family]]:
    """Scrape-time collector over a live ``ModelRegistry``."""
    return lambda: registry_families(model_registry.metrics())
