"""Serving metrics primitives: a sliding latency window and a plain
counter bag, both thread-safe and snapshot-oriented (the control plane
exposes point-in-time dicts, consumable as-is by ``GET /metrics``)."""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional


class LatencyWindow:
    """Sliding window of the most recent N request latencies with
    percentile snapshots.

    A bounded deque, not a histogram: serving windows are small enough
    (default 2048 samples) that exact percentiles over the raw samples
    are cheaper and more faithful than bucket interpolation, and the
    window self-ages — a traffic spike's tail latencies wash out after
    N fresh requests instead of polluting a cumulative histogram
    forever.
    """

    def __init__(self, maxlen: int = 2048):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._count = 0
        self._total_s = 0.0

    def add(self, seconds: float):
        with self._lock:
            self._samples.append(seconds)
            self._count += 1
            self._total_s += seconds

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            data = sorted(self._samples)
            count, total = self._count, self._total_s

        def pick(pct):
            if not data:
                return None
            k = min(len(data) - 1,
                    max(0, int(round((pct / 100.0) * (len(data) - 1)))))
            return round(data[k] * 1e3, 3)

        return {"count": count,
                "mean_ms": (round(total / count * 1e3, 3)
                            if count else None),
                "p50_ms": pick(50), "p90_ms": pick(90),
                "p99_ms": pick(99),
                "window": len(data)}


class Counters:
    """A named bag of monotonically-increasing integers."""

    def __init__(self, *names: str):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {n: 0 for n in names}

    def inc(self, name: str, by: int = 1):
        with self._lock:
            self._c[name] = self._c.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)
