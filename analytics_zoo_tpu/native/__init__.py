"""ctypes binding for the native host-side image pipeline (zoo_native.cc).

The reference delegated image decode to OpenCV through JNI
(feature/image/OpenCVMethod.scala); here the equivalent C++ library is
built on demand with the system toolchain and bound via ctypes (pybind11
is not available in this environment).  Everything degrades gracefully:
``available()`` is False when the toolchain or libjpeg/libpng are missing
and callers fall back to PIL.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "zoo_native.cc")
_LIB_PATH = os.path.join(_DIR, "libzoo_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> None:
    # build to a per-process temp path and rename atomically: concurrent
    # first-use builds from several worker processes must never leave a
    # torn .so at the final path (its fresh mtime would defeat the
    # staleness check forever)
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", _SRC,
           "-o", tmp, "-ljpeg", "-lpng", "-lpthread"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise RuntimeError(f"native build failed: {proc.stderr[-2000:]}")
    os.replace(tmp, _LIB_PATH)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            stale = (not os.path.exists(_LIB_PATH) or
                     os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC))
            if stale:
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            lib.zoo_decode_rgb.restype = ctypes.c_int
            lib.zoo_decode_rgb.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
            lib.zoo_free.argtypes = [ctypes.c_void_p]
            lib.zoo_resize_bilinear.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
            lib.zoo_decode_batch.restype = ctypes.c_int
            lib.zoo_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_size_t), ctypes.c_int,
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_float, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float)]
            lib.zoo_native_abi_version.restype = ctypes.c_int
            if lib.zoo_native_abi_version() != 1:
                raise RuntimeError("native ABI mismatch")
            _lib = lib
        except Exception as e:  # toolchain/libs absent: PIL fallback
            _build_error = str(e)
    return _lib


def available() -> bool:
    """True when the native library is (or can be) loaded."""
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def decode_image(data: bytes) -> np.ndarray:
    """Decode a JPEG/PNG blob to an (H, W, 3) uint8 RGB array."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    out = ctypes.c_void_p()
    w = ctypes.c_int()
    h = ctypes.c_int()
    rc = lib.zoo_decode_rgb(data, len(data), ctypes.byref(out),
                            ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        raise ValueError("image decode failed (not a valid JPEG/PNG?)")
    try:
        buf = ctypes.cast(out, ctypes.POINTER(
            ctypes.c_uint8 * (w.value * h.value * 3))).contents
        return np.frombuffer(buf, dtype=np.uint8).reshape(
            h.value, w.value, 3).copy()
    finally:
        lib.zoo_free(out)


def decode_resize_normalize_batch(
        blobs: Sequence[bytes], size, mean: Optional[Sequence[float]] = None,
        std: Optional[Sequence[float]] = None, scale: float = 1.0,
        num_threads: int = 0,
        errors: str = "raise") -> np.ndarray:
    """Decode + resize + normalize a batch of image blobs into float32 NHWC.

    Per pixel channel c: ``(pixel * scale - mean[c]) / std[c]`` (means/stds
    in the same 0-255 scale the reference's ChannelNormalize uses when
    scale=1).  ``errors='zero'`` zero-fills undecodable slots instead of
    raising.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    h, w = (size, size) if isinstance(size, int) else tuple(size)
    n = len(blobs)
    out = np.empty((n, h, w, 3), dtype=np.float32)
    if n == 0:
        return out
    blob_arr = (ctypes.c_char_p * n)(*[bytes(b) for b in blobs])
    len_arr = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
    mean_p = ((ctypes.c_float * 3)(*[float(v) for v in mean])
              if mean is not None else None)
    std_p = ((ctypes.c_float * 3)(*[float(v) for v in std])
             if std is not None else None)
    failures = lib.zoo_decode_batch(
        blob_arr, len_arr, n, h, w, mean_p, std_p,
        ctypes.c_float(scale), num_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if failures and errors == "raise":
        raise ValueError(f"{failures}/{n} images failed to decode")
    return out


def resize_bilinear(img: np.ndarray, size) -> np.ndarray:
    """Bilinear-resize an (H, W, 3) uint8 array (half-pixel centers)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    h, w = (size, size) if isinstance(size, int) else tuple(size)
    img = np.ascontiguousarray(img, dtype=np.uint8)
    sh, sw, c = img.shape
    if c != 3:
        raise ValueError("expected (H, W, 3) RGB input")
    dst = np.empty((h, w, 3), dtype=np.uint8)
    lib.zoo_resize_bilinear(
        img.ctypes.data_as(ctypes.c_char_p), sw, sh,
        dst.ctypes.data_as(ctypes.c_char_p), w, h)
    return dst
