// Native host-side image pipeline: decode + resize + normalize.
//
// TPU-native equivalent of the reference's OpenCV JNI path
// (zoo/.../feature/image/OpenCVMethod.scala: imdecode; ImageBytesToMat /
// ImageResize / ImageChannelNormalize transformers): the accelerator wants
// ready float batches in HBM, so the CPU-side decode must keep up with the
// device.  This library decodes JPEG (libjpeg) / PNG (libpng) blobs,
// bilinear-resizes, and normalizes to a float32 NHWC batch with a
// std::thread worker pool, called from Python via ctypes (no pybind11 in
// this environment).
//
// Build: g++ -O3 -fPIC -shared zoo_native.cc -o libzoo_native.so
//        -ljpeg -lpng -lpthread        (driven by native/__init__.py)

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// JPEG

struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* err = reinterpret_cast<JerrMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

void jerr_emit(j_common_ptr, int) {}  // silence warnings

// Decode a JPEG blob to tightly-packed RGB8.  Returns malloc'd buffer or
// nullptr.
uint8_t* decode_jpeg(const uint8_t* data, size_t len, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  jerr.pub.emit_message = jerr_emit;
  // volatile: modified between setjmp and longjmp — without it the
  // longjmp cleanup path may free a stale register value (C11 7.13.2.1)
  uint8_t* volatile out = nullptr;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    free(out);
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // grayscale/YCbCr -> RGB in-decoder
  jpeg_start_decompress(&cinfo);
  const int width = cinfo.output_width;
  const int height = cinfo.output_height;
  const int stride = width * 3;
  out = static_cast<uint8_t*>(malloc(static_cast<size_t>(stride) * height));
  if (!out) longjmp(jerr.setjmp_buffer, 1);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + static_cast<size_t>(stride) * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *w = width;
  *h = height;
  return out;
}

// ---------------------------------------------------------------------------
// PNG (simplified libpng16 API)

uint8_t* decode_png(const uint8_t* data, size_t len, int* w, int* h) {
  png_image image;
  memset(&image, 0, sizeof image);
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data, len)) return nullptr;
  image.format = PNG_FORMAT_RGB;
  uint8_t* out = static_cast<uint8_t*>(malloc(PNG_IMAGE_SIZE(image)));
  if (!out) {
    png_image_free(&image);
    return nullptr;
  }
  if (!png_image_finish_read(&image, nullptr, out, 0, nullptr)) {
    free(out);
    png_image_free(&image);
    return nullptr;
  }
  *w = static_cast<int>(image.width);
  *h = static_cast<int>(image.height);
  return out;
}

uint8_t* decode_any(const uint8_t* data, size_t len, int* w, int* h) {
  if (len >= 2 && data[0] == 0xFF && data[1] == 0xD8)
    return decode_jpeg(data, len, w, h);
  if (len >= 4 && data[0] == 0x89 && data[1] == 'P' && data[2] == 'N' &&
      data[3] == 'G')
    return decode_png(data, len, w, h);
  return nullptr;
}

// ---------------------------------------------------------------------------
// bilinear resize, RGB8 -> RGB8 (align_corners=false / half-pixel centers,
// matching PIL/OpenCV default)

void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                     int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      const float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * sw + x0) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * sw + x1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * sw + x0) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * sw + x1) * 3;
      uint8_t* q = dst + (static_cast<size_t>(y) * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        q[c] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode one blob to RGB8.  *out is malloc'd (free with zoo_free).
// Returns 0 on success, -1 on decode failure.
int zoo_decode_rgb(const uint8_t* data, size_t len, uint8_t** out, int* w,
                   int* h) {
  *out = decode_any(data, len, w, h);
  return *out ? 0 : -1;
}

void zoo_free(void* p) { free(p); }

void zoo_resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                         int dw, int dh) {
  resize_bilinear(src, sw, sh, dst, dw, dh);
}

// Decode n blobs, resize each to (out_h, out_w), normalize
// (pixel * scale - mean[c]) / stdv[c], write float32 NHWC into out.
// Worker pool of num_threads (<=0: hardware_concurrency).  Returns 0 when
// all images decoded; otherwise the count of failures (their slots are
// zero-filled).
int zoo_decode_batch(const uint8_t* const* blobs, const size_t* lens, int n,
                     int out_h, int out_w, const float* mean,
                     const float* stdv, float scale, int num_threads,
                     float* out) {
  const size_t img_elems = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int> next(0);
  std::atomic<int> failures(0);
  float m[3] = {0, 0, 0}, inv_s[3] = {1, 1, 1};
  for (int c = 0; c < 3; ++c) {
    if (mean) m[c] = mean[c];
    if (stdv) inv_s[c] = stdv[c] != 0 ? 1.0f / stdv[c] : 1.0f;
  }

  auto worker = [&]() {
    std::vector<uint8_t> resized(img_elems);
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) return;
      float* dst = out + img_elems * i;
      int w = 0, h = 0;
      uint8_t* rgb = decode_any(blobs[i], lens[i], &w, &h);
      if (!rgb) {
        memset(dst, 0, img_elems * sizeof(float));
        failures.fetch_add(1);
        continue;
      }
      const uint8_t* pixels = rgb;
      if (w != out_w || h != out_h) {
        resize_bilinear(rgb, w, h, resized.data(), out_w, out_h);
        pixels = resized.data();
      }
      for (size_t j = 0; j < img_elems; j += 3) {
        dst[j] = (pixels[j] * scale - m[0]) * inv_s[0];
        dst[j + 1] = (pixels[j + 1] * scale - m[1]) * inv_s[1];
        dst[j + 2] = (pixels[j + 2] * scale - m[2]) * inv_s[2];
      }
      free(rgb);
    }
  };

  int threads = num_threads > 0
                    ? num_threads
                    : static_cast<int>(std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  if (threads > n) threads = n;
  if (threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (int t = 0; t < threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

int zoo_native_abi_version() { return 1; }

}  // extern "C"
