"""Developer tooling for the analytics_zoo_tpu codebase.

Submodules import lazily; the zoolint static analyzer itself is
pure-stdlib AST (only the runtime ``zoolint.sanitize`` half touches
jax, and only when entered).
"""
