"""Finding: one zoolint diagnostic, with a baseline-stable fingerprint.

Baselines must survive unrelated edits, so the suppression key is
``(code, path, symbol)`` — the enclosing ``Class.method`` qualname —
NOT the line number, which shifts on every edit above the finding.
Line/col are carried for display only.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str        # stable rule id, e.g. "ZL401"
    path: str        # repo-relative, forward slashes
    line: int
    col: int
    symbol: str      # enclosing qualname ("Class.method", "func", "<module>")
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        """The baseline-matching fingerprint."""
        return (self.code, self.path, self.symbol)

    @property
    def docs(self) -> str:
        """The rule-catalog docs anchor for this finding's code."""
        from .catalog import anchor_for
        return anchor_for(self.code)

    def to_dict(self) -> dict:
        """The --format json shape: the dataclass fields plus the
        docs anchor (CI links findings straight to the catalog)."""
        return {**dataclasses.asdict(self), "docs": self.docs}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"[{self.symbol}] {self.message}")
