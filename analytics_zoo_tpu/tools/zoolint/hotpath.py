"""Hot-path rules (project-wide, call-graph based).

  ZL301  ``block_until_ready`` reachable from a serving hot entry point —
         a forced device sync on the request path serializes dispatch
         against compute.
  ZL302  implicit device→host materialization in a hot function:
         np.asarray / np.array / float() wrapped DIRECTLY around a
         dispatch call (``np.asarray(self._fn(x))``) — fetch explicitly
         via jax.device_get so transfer guards (and readers) see it.
  ZL601  bare ``print(...)`` / stdlib ``logging`` call in a hot
         function: free-text output from the request path cannot be
         joined back to the request that produced it (and ``print``
         grabs a global interpreter I/O lock mid-dispatch).  The
         sanctioned path is the structured logger
         (``analytics_zoo_tpu.observability.log.get_logger``), whose
         records carry the current request id.

The call graph is name-based and deliberately over-approximate: an edge
``f -> g`` exists when f's body calls anything whose final name is g
(``self._cache.run`` reaches every ``run`` in the package).  That
over-approximation errs toward marking code hot, which is the right
direction for a lint — the baseline absorbs the justified hits.

Hot entry points are matched by FINAL name so the rule follows renames
and new implementations: ``predict``, ``predict_ex``, ``_loop`` (the
coalescer dispatcher), ``submit``, ``dispatch_padded``, plus the
multi-replica scheduler loop's own pieces — ``dispatch`` (the
ReplicaSet per-replica dispatch) and ``pack`` (the staging arena fill,
dispatcher-thread hot) — so ZL301/302/601 cover the device-parallel
path even if the coalescer loop is later refactored around it.  The
elastic layer adds its own entries: ``tick`` (the autoscaler control
step — it primes replicas inline, so a stray sync or print there
stalls scale-ups), ``_resolve_hedged`` (the hedge dispatch/first-wins
resolve), and ``maybe_reprobe`` (the health-probe driver) — all three
run on or block serving threads even though none is reachable from
``predict`` by name alone.  The continuous-batching decode engine adds
``_loop_inner`` (the per-step dispatcher loop — a stray sync there
stalls EVERY live stream, not one request) and ``_admit_slot`` (the
prefill + slot-insert path each arriving sequence rides); ``submit``
was already an entry, so the TokenStream producer side is covered by
the existing BFS.  The persistent executable store adds ``lookup``
(the read-through consult under a compile miss — it runs with a
compile lock held, so a stray sync or free-text log there stalls
every caller racing the same signature) and ``rehydrate`` (bytes back
into a loaded executable, the path a warm deploy serves from).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .context import ModuleContext, QualnameVisitor, last_name
from .findings import Finding

DEFAULT_HOT_ENTRIES = ("predict", "predict_ex", "_loop", "submit",
                       "dispatch_padded", "dispatch", "pack",
                       "tick", "_resolve_hedged", "maybe_reprobe",
                       "_loop_inner", "_admit_slot",
                       "lookup", "rehydrate",
                       # fault-tolerant training: the launcher's
                       # supervision poll loop and the per-step worker
                       # heartbeat both sit on latency-critical paths
                       # (detection latency / the training step)
                       "_supervise", "heartbeat",
                       # cross-process observability: the flight
                       # recorder's framed append runs once per
                       # training step (and per finished span), and
                       # the aggregator merge loop runs per pod scrape
                       # — a stray sync or free-text log in either
                       # taxes every step / every scrape
                       "_append", "merge_snapshots",
                       # fleet serving: the router's routed data path
                       # (pick + wire call + retry-on-sibling) and the
                       # worker's per-connection request/reply loop
                       # both run once per fleet request
                       "_route_call", "_serve_conn",
                       # decode engine v2: the prefix-pool lookup runs
                       # once per pool-eligible admission, and the
                       # speculative window's host fan-out once per
                       # verify dispatch — a stray sync or free-text
                       # log in either taxes every admission / window
                       "_prefix_lookup", "_process_spec",
                       # weight pager (serving density): the cold-
                       # request fault-in and the demotion path — both
                       # sit between an admitted request and its first
                       # byte of service, so a stray host sync or
                       # free-text log there stalls every caller
                       # queued on the same fault
                       "fault_in", "_try_evict",
                       # fleet v2 binary wire: the out-of-band payload
                       # encode/decode runs once per negotiated
                       # predict/generate frame in BOTH directions —
                       # the whole point is shaving per-hop copies, so
                       # a stray materialization or free-text log here
                       # pays twice per request
                       "encode_binary", "decode_binary",
                       # sharded serving: the group-atomic placement
                       # check (gates every paged install) and the
                       # span labeler (stamped on every dispatch) run
                       # on the request path — a stray sync or free-
                       # text log in either taxes every sharded
                       # request
                       "placement_complete", "span_labels",
                       # distributed tracing: the worker-side reply
                       # piggyback builder runs once per TRACED serve
                       # reply and the router-side inline stitch once
                       # per traced response — both sit inside the
                       # traced/untraced throughput-ratio gate, so a
                       # stray sync or free-text log in either is
                       # exactly the overhead the gate bounds
                       "reply_trace", "nest_summary",
                       # sharded training: the compiled step body and
                       # its gradient-accumulation scan body (traced
                       # once, but a host sync or print there lands
                       # INSIDE the training hot loop / the trace),
                       # plus the prefetch-thread microbatch split that
                       # runs once per step between h2d and dispatch
                       "train_step", "micro_step",
                       "_split_microbatches")
# callees whose result is a device value mid-flight: materializing their
# return implicitly is the ZL302 pattern
_DISPATCHY = {"predict_fn", "dispatch_padded"}
_MATERIALIZERS = {"numpy.asarray", "numpy.array"}


def _is_dispatchy(name: str) -> bool:
    return (name in _DISPATCHY or name.endswith("_fn")
            or name.startswith("dispatch"))


class _DefCollector(QualnameVisitor):
    """(qualname -> {called final names}) for one module."""

    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.defs: Dict[str, ast.AST] = {}

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        self.defs.setdefault(self.qualname, node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def _callees(fd: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fd):
        if isinstance(node, ast.Call):
            name = last_name(node.func)
            if name:
                out.add(name)
    return out


def collect_hot_defs(ctxs: List[ModuleContext],
                     hot_entries: Tuple[str, ...] = DEFAULT_HOT_ENTRIES):
    """The shared project pass: every def keyed by (path, qualname),
    the set of hot-reachable keys (name-based BFS from the entry
    points), and a path -> ModuleContext map.  Used by every hot-path
    rule (ZL3xx, ZL601)."""
    # 1. collect every def in the project, keyed by (path, qualname)
    defs: Dict[Tuple[str, str], ast.AST] = {}
    by_final: Dict[str, List[Tuple[str, str]]] = {}
    ctx_of: Dict[str, ModuleContext] = {}
    for ctx in ctxs:
        ctx_of[ctx.path] = ctx
        col = _DefCollector(ctx)
        col.visit(ctx.tree)
        for qual, fd in col.defs.items():
            key = (ctx.path, qual)
            defs[key] = fd
            by_final.setdefault(qual.rsplit(".", 1)[-1], []).append(key)

    # 2. BFS from the entry points over name-resolved call edges
    hot: Set[Tuple[str, str]] = set()
    frontier = [k for name in hot_entries for k in by_final.get(name, [])]
    hot.update(frontier)
    while frontier:
        key = frontier.pop()
        for callee in _callees(defs[key]):
            for nxt in by_final.get(callee, []):
                if nxt not in hot:
                    hot.add(nxt)
                    frontier.append(nxt)
    return defs, hot, ctx_of


def rule_hot_path(ctxs: List[ModuleContext],
                  hot_entries: Tuple[str, ...] = DEFAULT_HOT_ENTRIES,
                  hot_defs=None) -> List[Finding]:
    """``hot_defs``: the precomputed ``collect_hot_defs`` triple — the
    engine computes it once and shares it with every hot-path rule;
    standalone callers may omit it."""
    defs, hot, ctx_of = (hot_defs if hot_defs is not None
                         else collect_hot_defs(ctxs, hot_entries))

    # flag sync / implicit-materialize sites inside hot defs
    findings: List[Finding] = []
    for (path, qual) in sorted(hot):
        fd = defs[(path, qual)]
        ctx = ctx_of[path]
        for node in ast.walk(fd):
            if not isinstance(node, ast.Call):
                continue
            if last_name(node.func) == "block_until_ready":
                findings.append(Finding(
                    "ZL301", path, node.lineno, node.col_offset, qual,
                    "block_until_ready on the serving hot path "
                    f"(reachable from {'/'.join(hot_entries)}): a forced "
                    "device sync serializes dispatch against compute — "
                    "fetch via jax.device_get at the fan-out point, or "
                    "baseline with a justification if the sync is the "
                    "point (e.g. compile-time measurement)"))
                continue
            resolved = ctx.resolve(node.func)
            wraps_dispatch = (
                node.args and isinstance(node.args[0], ast.Call)
                and (lambda n: n is not None and _is_dispatchy(n))(
                    last_name(node.args[0].func)))
            if wraps_dispatch and (
                    resolved in _MATERIALIZERS
                    or (isinstance(node.func, ast.Name)
                        and node.func.id == "float")):
                findings.append(Finding(
                    "ZL302", path, node.lineno, node.col_offset, qual,
                    "implicit device->host materialization of a "
                    "dispatch result on the hot path — wrap the fetch "
                    "in jax.device_get (explicit transfers pass "
                    "transfer guards; implicit ones abort them)"))
    return findings


_LOG_METHODS = {"debug", "info", "warning", "warn", "error",
                "exception", "critical", "log"}


def _stdlib_logger_names(ctx: ModuleContext) -> Set[str]:
    """Local names bound to ``logging.getLogger(...)`` results — both
    ``log = logging.getLogger(...)`` and ``self._log = ...`` (matched
    by final attribute name)."""
    names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and ctx.resolve(node.value.func) == "logging.getLogger"):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Attribute):
                names.add(t.attr)
    return names


def _is_stdlib_log_call(ctx: ModuleContext, node: ast.Call,
                        logger_names: Set[str]) -> bool:
    func = node.func
    resolved = ctx.resolve(func)
    # logging.info(...) / logging.getLogger("x").info(...)
    if resolved is not None and resolved.startswith("logging."):
        return resolved.rsplit(".", 1)[-1] in _LOG_METHODS
    if not (isinstance(func, ast.Attribute)
            and func.attr in _LOG_METHODS):
        return False
    recv = func.value
    if isinstance(recv, ast.Call) and \
            ctx.resolve(recv.func) == "logging.getLogger":
        return True  # logging.getLogger(...).warning(...)
    if isinstance(recv, ast.Name):
        return recv.id in logger_names
    if isinstance(recv, ast.Attribute):
        return recv.attr in logger_names  # self._log.info(...)
    return False


def rule_hot_logging(ctxs: List[ModuleContext],
                     hot_entries: Tuple[str, ...] = DEFAULT_HOT_ENTRIES,
                     hot_defs=None) -> List[Finding]:
    """ZL601: bare print / stdlib logging inside hot-reachable
    functions.  The structured logger
    (``analytics_zoo_tpu.observability.log.get_logger``) is exempt by
    construction: its instances are not created via
    ``logging.getLogger`` in the flagged module, and its records carry
    the current request id — which is the point."""
    defs, hot, ctx_of = (hot_defs if hot_defs is not None
                         else collect_hot_defs(ctxs, hot_entries))
    logger_names = {ctx.path: _stdlib_logger_names(ctx) for ctx in ctxs}
    findings: List[Finding] = []
    for (path, qual) in sorted(hot):
        fd = defs[(path, qual)]
        ctx = ctx_of[path]
        for node in ast.walk(fd):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                findings.append(Finding(
                    "ZL601", path, node.lineno, node.col_offset, qual,
                    "print() on the serving hot path (reachable from "
                    f"{'/'.join(hot_entries)}): free-text output "
                    "cannot be joined back to its request and takes a "
                    "global I/O lock mid-dispatch — use the "
                    "structured logger (analytics_zoo_tpu."
                    "observability.log.get_logger), whose records "
                    "carry the request id; baseline with a "
                    "justification if the output IS the tool's UI"))
            elif _is_stdlib_log_call(ctx, node, logger_names[path]):
                findings.append(Finding(
                    "ZL601", path, node.lineno, node.col_offset, qual,
                    "stdlib logging call on the serving hot path — "
                    "free-text records drop the request id.  Use the "
                    "structured logger (analytics_zoo_tpu."
                    "observability.log.get_logger) so the record "
                    "carries request_id and joins the trace; baseline "
                    "with a justification for intentional module-level "
                    "diagnostics"))
    return findings
