"""Hot-path host-sync rules (project-wide, call-graph based).

  ZL301  ``block_until_ready`` reachable from a serving hot entry point —
         a forced device sync on the request path serializes dispatch
         against compute.
  ZL302  implicit device→host materialization in a hot function:
         np.asarray / np.array / float() wrapped DIRECTLY around a
         dispatch call (``np.asarray(self._fn(x))``) — fetch explicitly
         via jax.device_get so transfer guards (and readers) see it.

The call graph is name-based and deliberately over-approximate: an edge
``f -> g`` exists when f's body calls anything whose final name is g
(``self._cache.run`` reaches every ``run`` in the package).  That
over-approximation errs toward marking code hot, which is the right
direction for a lint — the baseline absorbs the justified hits.

Hot entry points are matched by FINAL name so the rule follows renames
and new implementations: ``predict``, ``predict_ex``, ``_loop`` (the
coalescer dispatcher), ``submit``, and ``dispatch_padded``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .context import ModuleContext, QualnameVisitor, last_name
from .findings import Finding

DEFAULT_HOT_ENTRIES = ("predict", "predict_ex", "_loop", "submit",
                       "dispatch_padded")
# callees whose result is a device value mid-flight: materializing their
# return implicitly is the ZL302 pattern
_DISPATCHY = {"predict_fn", "dispatch_padded"}
_MATERIALIZERS = {"numpy.asarray", "numpy.array"}


def _is_dispatchy(name: str) -> bool:
    return (name in _DISPATCHY or name.endswith("_fn")
            or name.startswith("dispatch"))


class _DefCollector(QualnameVisitor):
    """(qualname -> {called final names}) for one module."""

    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.defs: Dict[str, ast.AST] = {}

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        self.defs.setdefault(self.qualname, node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def _callees(fd: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fd):
        if isinstance(node, ast.Call):
            name = last_name(node.func)
            if name:
                out.add(name)
    return out


def rule_hot_path(ctxs: List[ModuleContext],
                  hot_entries: Tuple[str, ...] = DEFAULT_HOT_ENTRIES
                  ) -> List[Finding]:
    # 1. collect every def in the project, keyed by (path, qualname)
    defs: Dict[Tuple[str, str], ast.AST] = {}
    by_final: Dict[str, List[Tuple[str, str]]] = {}
    ctx_of: Dict[str, ModuleContext] = {}
    for ctx in ctxs:
        ctx_of[ctx.path] = ctx
        col = _DefCollector(ctx)
        col.visit(ctx.tree)
        for qual, fd in col.defs.items():
            key = (ctx.path, qual)
            defs[key] = fd
            by_final.setdefault(qual.rsplit(".", 1)[-1], []).append(key)

    # 2. BFS from the entry points over name-resolved call edges
    hot: Set[Tuple[str, str]] = set()
    frontier = [k for name in hot_entries for k in by_final.get(name, [])]
    hot.update(frontier)
    while frontier:
        key = frontier.pop()
        for callee in _callees(defs[key]):
            for nxt in by_final.get(callee, []):
                if nxt not in hot:
                    hot.add(nxt)
                    frontier.append(nxt)

    # 3. flag sync / implicit-materialize sites inside hot defs
    findings: List[Finding] = []
    for (path, qual) in sorted(hot):
        fd = defs[(path, qual)]
        ctx = ctx_of[path]
        for node in ast.walk(fd):
            if not isinstance(node, ast.Call):
                continue
            if last_name(node.func) == "block_until_ready":
                findings.append(Finding(
                    "ZL301", path, node.lineno, node.col_offset, qual,
                    "block_until_ready on the serving hot path "
                    f"(reachable from {'/'.join(hot_entries)}): a forced "
                    "device sync serializes dispatch against compute — "
                    "fetch via jax.device_get at the fan-out point, or "
                    "baseline with a justification if the sync is the "
                    "point (e.g. compile-time measurement)"))
                continue
            resolved = ctx.resolve(node.func)
            wraps_dispatch = (
                node.args and isinstance(node.args[0], ast.Call)
                and (lambda n: n is not None and _is_dispatchy(n))(
                    last_name(node.args[0].func)))
            if wraps_dispatch and (
                    resolved in _MATERIALIZERS
                    or (isinstance(node.func, ast.Name)
                        and node.func.id == "float")):
                findings.append(Finding(
                    "ZL302", path, node.lineno, node.col_offset, qual,
                    "implicit device->host materialization of a "
                    "dispatch result on the hot path — wrap the fetch "
                    "in jax.device_get (explicit transfers pass "
                    "transfer guards; implicit ones abort them)"))
    return findings
