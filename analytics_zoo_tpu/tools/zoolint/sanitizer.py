"""zoolint.sanitize(): runtime sanitizer for pinned hot loops.

The static rules catch patterns; this catches FACTS — a context manager
that asserts "this block performed zero unexpected XLA compiles and no
implicit host<->device transfers":

* **compiles** — counted via jax's monitoring events
  (``/jax/core/compile/backend_compile_duration`` fires exactly once per
  real XLA compile; cache hits fire nothing).  More than ``max_compiles``
  raises :class:`RecompileDetected` at block exit, listing the events.
* **transfers** — jax's transfer guards set to ``disallow`` for all
  three directions via ``jax.config.update`` (the process-wide default,
  NOT the thread-local ``jax.transfer_guard`` context) so worker threads
  — the coalescer dispatcher — are covered too.  An implicit transfer
  raises an ``XlaRuntimeError`` mentioning "Disallowed ... transfer" at
  the offending call.  Explicit ``jax.device_put`` / ``jax.device_get``
  always pass: the point is that data movement must be *visible*.

Backend caveat: on the CPU backend device->host is zero-copy — there is
no transfer to guard — so d2h violations are only observable on real
accelerators.  Host->device IS enforced on CPU (jit arguments arriving
as numpy count), which is why the serving dispatch path uploads via
explicit ``device_put`` (see BucketedExecutableCache._dispatch).

Usage::

    with zoolint.sanitize(max_compiles=0) as rep:
        for x in pinned_hot_loop:
            model.predict(x)
    assert rep.compiles == 0    # redundant — exit would have raised

Tests get it as the ``zoolint_sanitize`` fixture; ``bench.py serving
--selfcheck`` runs the serving hot loop under it.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


class SanitizeError(RuntimeError):
    """Base for sanitizer verdicts."""


class RecompileDetected(SanitizeError):
    """The sanitized block compiled more than its budget allows."""


class InvariantLeakDetected(SanitizeError):
    """A gauge invariant moved across the sanitized block: an
    in-flight/slot/ticket counter (or the live thread count) did not
    return to its entry value over a quiesced serve window — the
    runtime signature of the ZL701/ZL702 leak class (a seat taken on
    an exception path and never given back shows up here as a counter
    permanently up by one)."""


class SanitizeReport:
    """Live view into the sanitized block (yielded by sanitize())."""

    def __init__(self, label: str):
        self.label = label
        self._lock = threading.Lock()
        self._events: List[Tuple[str, float]] = []

    def _record(self, key: str, duration: float):
        with self._lock:
            if len(self._events) < 1000:  # cap pathological loops
                self._events.append((key, duration))

    @property
    def compiles(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def events(self) -> List[Tuple[str, float]]:
        with self._lock:
            return list(self._events)


_GUARD_CONFIGS = ("jax_transfer_guard_host_to_device",
                  "jax_transfer_guard_device_to_device",
                  "jax_transfer_guard_device_to_host")
_COMPILE_EVENT_SUBSTR = "backend_compile"


@contextlib.contextmanager
def sanitize(max_compiles: int = 0,
             transfer_guard: Optional[str] = "disallow",
             label: str = "zoolint.sanitize",
             invariants: Optional[Callable[[], Dict[str, Any]]] = None,
             invariant_threads: bool = True):
    """Assert the block stays compile- and transfer-clean (module doc).

    ``max_compiles``: XLA compiles the block may perform (0 for a warmed
    hot loop).  ``transfer_guard``: guard level for all three directions
    ("disallow" / "log" / None to leave transfers unguarded).  Yields a
    :class:`SanitizeReport`; raises :class:`RecompileDetected` on exit
    when the budget is exceeded.  Transfer violations raise inside jax
    at the offending call (XlaRuntimeError, "Disallowed ... transfer").

    **Invariant-snapshot mode** (``invariants=``): pass a zero-arg
    callable returning gauge values — in-flight counts, queue seats,
    slot occupancy, admission tickets — and the block asserts every
    one of them (plus, with ``invariant_threads``, the live
    ``threading.active_count()``) returns to its entry value by block
    exit, raising :class:`InvariantLeakDetected` otherwise.  The block
    must be QUIESCED at both ends (warmed before entry, drained before
    exit — a sequential closed-loop serve window is, by construction);
    a monotonic stat counter does not belong in the snapshot, only
    gauges that a leak-free window brings back to rest.  This is the
    runtime twin of the ZL701/ZL702 static rules: the lint proves no
    exception path CAN leak a seat, the snapshot proves this run
    DIDN'T.  Checked only on clean exit (an exception unwinding out of
    the block is its own report), after the compile budget.

    Guards are process-global while the block runs — don't nest, and
    don't run unrelated jax work concurrently with a sanitized block.
    """
    import jax
    from jax._src import monitoring as _monitoring

    report = SanitizeReport(label)
    pre_inv: Optional[Dict[str, Any]] = None
    if invariants is not None:
        pre_inv = dict(invariants())
        if invariant_threads:
            pre_inv["live_threads"] = threading.active_count()
    active = [True]  # unhook even if jax keeps the listener registered

    def _listener(key: str, duration: float, **kw):
        if active[0] and _COMPILE_EVENT_SUBSTR in key:
            report._record(key, duration)

    _monitoring.register_event_duration_secs_listener(_listener)
    prev = {name: getattr(jax.config, name) for name in _GUARD_CONFIGS}
    if transfer_guard is not None:
        for name in _GUARD_CONFIGS:
            jax.config.update(name, transfer_guard)
    try:
        yield report
    finally:
        active[0] = False
        if transfer_guard is not None:
            for name, value in prev.items():
                jax.config.update(name, value)
        unhook = getattr(_monitoring,
                         "_unregister_event_duration_listener_by_callback",
                         None)
        if unhook is not None:
            try:
                unhook(_listener)
            except Exception:
                pass  # the active flag already made it inert
    if report.compiles > max_compiles:
        lines = "\n  ".join(f"{k} ({d * 1e3:.1f} ms)"
                            for k, d in report.events[:10])
        raise RecompileDetected(
            f"{label}: {report.compiles} XLA compile(s) inside a block "
            f"budgeted for {max_compiles} — a shape/dtype escaped the "
            f"warmed bucket ladder, or a jit wrapper was rebuilt:\n  "
            f"{lines}")
    if pre_inv is not None:
        post_inv = dict(invariants())
        if invariant_threads:
            post_inv["live_threads"] = threading.active_count()
        leaks = {k: (pre_inv.get(k), post_inv.get(k))
                 for k in sorted(set(pre_inv) | set(post_inv))
                 if pre_inv.get(k) != post_inv.get(k)}
        if leaks:
            detail = ", ".join(f"{k}: {a!r} -> {b!r}"
                               for k, (a, b) in leaks.items())
            raise InvariantLeakDetected(
                f"{label}: {len(leaks)} invariant(s) moved across a "
                f"quiesced serve window ({detail}) — an in-flight/"
                "slot/ticket counter (or a thread) leaked; an "
                "exception path somewhere took a seat it never gave "
                "back (the ZL701/ZL702 bug class, live)")
