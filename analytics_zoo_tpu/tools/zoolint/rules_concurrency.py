"""Concurrency static rules.

Lock discipline
  ZL401  an attribute written both with and without its owning lock: the
         owning lock is the one held at the majority of write sites;
         sites missing it are flagged.  ``__init__`` writes (construction
         — no concurrent reader can exist yet) are exempt.
  ZL402  blocking device work (warmup / block_until_ready / device_get /
         fetch_rows / dispatch_padded / predict) performed while holding
         a lock — every other thread contending that lock now waits on
         the device.

Thread lifecycle
  ZL501  non-daemon thread that is never joined in its module: leaks at
         interpreter exit and pins the process on crash.
  ZL502  unbounded queue.Queue: under overload it converts memory into
         latency instead of shedding (see serving.admission).
"""

from __future__ import annotations

import ast
import collections
from typing import Dict, List, Optional, Set, Tuple

from .context import (ModuleContext, QualnameVisitor, dotted_name,
                      is_lock_ctor, last_name, lock_expr)
from .findings import Finding

_BLOCKING_DEVICE_CALLS = {"warmup", "block_until_ready", "device_get",
                          "fetch_rows", "dispatch_padded", "predict",
                          "predict_ex"}


# ----------------------------------------------------------------- ZL401
class _WriteSite:
    __slots__ = ("line", "col", "symbol", "locks", "in_init")

    def __init__(self, line, col, symbol, locks, in_init):
        self.line, self.col, self.symbol = line, col, symbol
        self.locks: Set[str] = locks
        self.in_init = in_init


class _LockDisciplineVisitor(QualnameVisitor):
    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        # (recv_kind, attr) -> [write sites]; recv_kind is the class
        # name for `self.x` writes and the bare variable name otherwise
        self.writes: Dict[Tuple[str, str], List[_WriteSite]] = \
            collections.defaultdict(list)
        self.lock_attrs: Set[str] = set()

    def _record(self, target: ast.AST):
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)):
            return
        recv, attr = target.value.id, target.attr
        if lock_expr(target) is not None:
            return  # assigning the lock itself
        if recv == "self":
            kind = self.class_stack[-1] if self.class_stack else "self"
        else:
            kind = recv
        in_init = bool(self.func_stack) and self.func_stack[0] == "__init__"
        self.writes[(kind, attr)].append(_WriteSite(
            target.lineno, target.col_offset, self.qualname,
            set(self.lock_stack), in_init))

    def visit_Assign(self, node: ast.Assign):
        if is_lock_ctor(self.ctx, node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    self.lock_attrs.add(t.attr)
        else:
            for t in node.targets:
                if isinstance(t, ast.Tuple):
                    for e in t.elts:
                        self._record(e)
                else:
                    self._record(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target)
        self.generic_visit(node)


def rule_lock_discipline(ctx: ModuleContext) -> List[Finding]:
    v = _LockDisciplineVisitor(ctx)
    v.visit(ctx.tree)
    findings: List[Finding] = []
    for (kind, attr), sites in sorted(v.writes.items()):
        live = [s for s in sites if not s.in_init]
        locked = [s for s in live if s.locks]
        if not locked or len(live) < 2:
            continue  # never locked (single-writer style) or single site
        counts = collections.Counter(
            lock for s in locked for lock in s.locks)
        owner, _ = counts.most_common(1)[0]
        offenders = [s for s in live if owner not in s.locks]
        if not offenders:
            continue
        owned = sum(1 for s in live if owner in s.locks)
        for s in offenders:
            held = f"under {sorted(s.locks)}" if s.locks else "with no lock"
            findings.append(Finding(
                "ZL401", ctx.path, s.line, s.col, s.symbol,
                f"attribute {kind}.{attr} is written {held} here but "
                f"under {owner} at {owned} other site(s) — a "
                "torn/lost update is one unlucky preemption away"))
    return findings


# ----------------------------------------------------------------- ZL402
class _BlockingUnderLockVisitor(QualnameVisitor):
    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call):
        name = last_name(node.func)
        if self.lock_stack and name in _BLOCKING_DEVICE_CALLS:
            self.findings.append(Finding(
                "ZL402", self.ctx.path, node.lineno, node.col_offset,
                self.qualname,
                f"blocking device call {name}() while holding "
                f"{sorted(set(self.lock_stack))}: every thread "
                "contending this lock now waits on device latency — "
                "move the dispatch outside the critical section"))
        self.generic_visit(node)


def rule_blocking_under_lock(ctx: ModuleContext) -> List[Finding]:
    v = _BlockingUnderLockVisitor(ctx)
    v.visit(ctx.tree)
    return v.findings


# ----------------------------------------------------------------- ZL501
def rule_thread_lifecycle(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    # every `<something>.join(` receiver dotted path seen in the module
    joined: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            recv = dotted_name(node.func.value)
            if recv:
                joined.add(recv)

    class V(QualnameVisitor):
        def visit_Call(self, node: ast.Call):
            if self.ctx.resolve(node.func) in ("threading.Thread",
                                               "Thread"):
                daemon = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                if not daemon and not self._target_joined(node):
                    findings.append(Finding(
                        "ZL501", self.ctx.path, node.lineno,
                        node.col_offset, self.qualname,
                        "non-daemon Thread that is never joined in this "
                        "module: it outlives its owner, pins interpreter "
                        "exit, and strands work on crash — pass "
                        "daemon=True or join it"))
            self.generic_visit(node)

        def _target_joined(self, call: ast.Call) -> bool:
            parent = self._assign_target_of(call)
            return parent is not None and parent in joined

        def _assign_target_of(self, call: ast.Call) -> Optional[str]:
            # the name/attr this Thread(...) was bound to, if any
            for node in ast.walk(self.ctx.tree):
                if isinstance(node, ast.Assign) and node.value is call:
                    for t in node.targets:
                        d = dotted_name(t)
                        if d:
                            return d
            return None

    V(ctx).visit(ctx.tree)
    return findings


# ----------------------------------------------------------------- ZL502
def rule_unbounded_queue(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []

    class V(QualnameVisitor):
        def visit_Call(self, node: ast.Call):
            resolved = self.ctx.resolve(node.func)
            if resolved in ("queue.Queue", "queue.LifoQueue",
                            "queue.PriorityQueue", "Queue"):
                bounded = bool(node.args) or any(
                    kw.arg == "maxsize" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value == 0)
                    for kw in node.keywords)
                if not bounded:
                    findings.append(Finding(
                        "ZL502", self.ctx.path, node.lineno,
                        node.col_offset, self.qualname,
                        "unbounded queue.Queue: under overload it "
                        "converts memory into latency instead of "
                        "shedding — pass maxsize (see "
                        "serving.admission for the argument)"))
            self.generic_visit(node)

    V(ctx).visit(ctx.tree)
    return findings
