"""Concurrency static rules.

Lock discipline
  ZL401  an attribute written both with and without its owning lock: the
         owning lock is the one held at the majority of write sites;
         sites missing it are flagged.  ``__init__`` writes (construction
         — no concurrent reader can exist yet) are exempt.
  ZL402  blocking device work (warmup / block_until_ready / device_get /
         fetch_rows / dispatch_padded / predict) performed while holding
         a lock — every other thread contending that lock now waits on
         the device.

Thread lifecycle
  ZL501  non-daemon thread that is never joined in its module: leaks at
         interpreter exit and pins the process on crash.
  ZL502  unbounded queue.Queue: under overload it converts memory into
         latency instead of shedding (see serving.admission).

Shared-state races (project-wide, v2)
  ZL721  check-then-deref: a truthiness/None test on a SHARED mutable
         attribute (one written under a lock somewhere in the project)
         followed by a re-read of the same attribute in the guarded
         region, instead of a local snapshot — the attribute can be
         nulled between the check and the deref (``autoscaler_for``
         reading ``entry.active`` twice was exactly this).  Checks made
         while lexically holding a lock are exempt (the lock excludes
         the writer), as are re-reads taken back under a lock inside
         the guarded region.
  ZL731  lock-order: the project-wide lock-acquisition graph (an edge
         A -> B whenever B is acquired while A is lexically held, built
         from the same ``with recv.lock:`` sets ZL401 uses, lock
         identity resolved to its owning class via the lock-constructor
         assignments).  A cycle means two threads can block on each
         other's second lock — a deadlock waiting for load.  Self-loops
         are exempt: RLock re-entry (``_grant_locked`` under
         ``_cond``) is a sanctioned idiom.
"""

from __future__ import annotations

import ast
import collections
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .context import (ModuleContext, QualnameVisitor, binding_targets,
                      dotted_name, is_lock_ctor, last_name, lock_expr)
from .findings import Finding

_BLOCKING_DEVICE_CALLS = {"warmup", "block_until_ready", "device_get",
                          "fetch_rows", "dispatch_padded", "predict",
                          "predict_ex"}


# ----------------------------------------------------------------- ZL401
class _WriteSite:
    __slots__ = ("line", "col", "symbol", "locks", "in_init")

    def __init__(self, line, col, symbol, locks, in_init):
        self.line, self.col, self.symbol = line, col, symbol
        self.locks: Set[str] = locks
        self.in_init = in_init


class _LockDisciplineVisitor(QualnameVisitor):
    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        # (recv_kind, attr) -> [write sites]; recv_kind is the class
        # name for `self.x` writes and the bare variable name otherwise
        self.writes: Dict[Tuple[str, str], List[_WriteSite]] = \
            collections.defaultdict(list)
        self.lock_attrs: Set[str] = set()

    def _record(self, target: ast.AST):
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)):
            return
        recv, attr = target.value.id, target.attr
        if lock_expr(target) is not None:
            return  # assigning the lock itself
        if recv == "self":
            kind = self.class_stack[-1] if self.class_stack else "self"
        else:
            kind = recv
        in_init = bool(self.func_stack) and self.func_stack[0] == "__init__"
        self.writes[(kind, attr)].append(_WriteSite(
            target.lineno, target.col_offset, self.qualname,
            set(self.lock_stack), in_init))

    def visit_Assign(self, node: ast.Assign):
        if is_lock_ctor(self.ctx, node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    self.lock_attrs.add(t.attr)
        else:
            for t in node.targets:
                if isinstance(t, ast.Tuple):
                    for e in t.elts:
                        self._record(e)
                else:
                    self._record(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target)
        self.generic_visit(node)


def rule_lock_discipline(ctx: ModuleContext) -> List[Finding]:
    v = _LockDisciplineVisitor(ctx)
    v.visit(ctx.tree)
    findings: List[Finding] = []
    for (kind, attr), sites in sorted(v.writes.items()):
        live = [s for s in sites if not s.in_init]
        locked = [s for s in live if s.locks]
        if not locked or len(live) < 2:
            continue  # never locked (single-writer style) or single site
        counts = collections.Counter(
            lock for s in locked for lock in s.locks)
        owner, _ = counts.most_common(1)[0]
        offenders = [s for s in live if owner not in s.locks]
        if not offenders:
            continue
        owned = sum(1 for s in live if owner in s.locks)
        for s in offenders:
            held = f"under {sorted(s.locks)}" if s.locks else "with no lock"
            findings.append(Finding(
                "ZL401", ctx.path, s.line, s.col, s.symbol,
                f"attribute {kind}.{attr} is written {held} here but "
                f"under {owner} at {owned} other site(s) — a "
                "torn/lost update is one unlucky preemption away"))
    return findings


# ----------------------------------------------------------------- ZL402
class _BlockingUnderLockVisitor(QualnameVisitor):
    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call):
        name = last_name(node.func)
        if self.lock_stack and name in _BLOCKING_DEVICE_CALLS:
            self.findings.append(Finding(
                "ZL402", self.ctx.path, node.lineno, node.col_offset,
                self.qualname,
                f"blocking device call {name}() while holding "
                f"{sorted(set(self.lock_stack))}: every thread "
                "contending this lock now waits on device latency — "
                "move the dispatch outside the critical section"))
        self.generic_visit(node)


def rule_blocking_under_lock(ctx: ModuleContext) -> List[Finding]:
    v = _BlockingUnderLockVisitor(ctx)
    v.visit(ctx.tree)
    return v.findings


# ----------------------------------------------------------------- ZL501
def rule_thread_lifecycle(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    # every `<something>.join(` receiver dotted path seen in the module
    joined: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            recv = dotted_name(node.func.value)
            if recv:
                joined.add(recv)

    class V(QualnameVisitor):
        def visit_Call(self, node: ast.Call):
            if self.ctx.resolve(node.func) in ("threading.Thread",
                                               "Thread"):
                daemon = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                if not daemon and not self._target_joined(node):
                    findings.append(Finding(
                        "ZL501", self.ctx.path, node.lineno,
                        node.col_offset, self.qualname,
                        "non-daemon Thread that is never joined in this "
                        "module: it outlives its owner, pins interpreter "
                        "exit, and strands work on crash — pass "
                        "daemon=True or join it"))
            self.generic_visit(node)

        def _target_joined(self, call: ast.Call) -> bool:
            parent = self._assign_target_of(call)
            return parent is not None and parent in joined

        def _assign_target_of(self, call: ast.Call) -> Optional[str]:
            # the name/attr this Thread(...) was bound to, if any
            for node in ast.walk(self.ctx.tree):
                if isinstance(node, ast.Assign) and node.value is call:
                    for t in node.targets:
                        d = dotted_name(t)
                        if d:
                            return d
            return None

    V(ctx).visit(ctx.tree)
    return findings


# ----------------------------------------------------------------- ZL721
def collect_shared_attrs(ctxs: Sequence[ModuleContext]) -> Set[str]:
    """Attribute names written under a held lock anywhere in the
    project (``__init__`` construction writes excluded) — the
    population ZL721 treats as shared mutable state.  Attr-name keyed:
    the lock tells us SOMEONE considers this attribute contended, and
    the check-then-deref pattern is wrong wherever that attribute is
    then read unlocked."""
    shared: Set[str] = set()
    for ctx in ctxs:
        class V(QualnameVisitor):
            def _record(self, t):
                if (self.lock_stack
                        and isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and not (self.func_stack
                                 and self.func_stack[0] == "__init__")):
                    shared.add(t.attr)

            def visit_Assign(self, node):
                for t in binding_targets(node):
                    self._record(t)
                self.generic_visit(node)

            def visit_AugAssign(self, node):
                self._record(node.target)
                self.generic_visit(node)

        V(ctx).visit(ctx.tree)
    return shared


def _none_check(test: ast.AST
                ) -> List[Tuple[str, bool, List[ast.AST]]]:
    """(dotted attr, guarded_branch_is_body, tail_tests) candidates of
    a test expression: ``x.attr`` / ``x.attr is not None`` guard the
    body, ``not x.attr`` / ``x.attr is None`` guard the else.  For an
    ``and`` chain, operand i's candidate guards the operands AFTER it
    (returned as tail_tests) plus the body — never itself, or the safe
    ``if flag and x.attr is not None:`` idiom would self-match."""
    out: List[Tuple[str, bool, List[ast.AST]]] = []

    def _cand(node) -> Optional[Tuple[str, bool]]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            return dotted_name(node), True
        if (isinstance(node, ast.UnaryOp)
                and isinstance(node.op, ast.Not)
                and isinstance(node.operand, ast.Attribute)
                and isinstance(node.operand.value, ast.Name)):
            return dotted_name(node.operand), False
        if (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.left, ast.Attribute)
                and isinstance(node.left.value, ast.Name)
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None):
            if isinstance(node.ops[0], ast.IsNot):
                return dotted_name(node.left), True
            if isinstance(node.ops[0], ast.Is):
                return dotted_name(node.left), False
        return None

    c = _cand(test)
    if c is not None and c[0] is not None:
        out.append((c[0], c[1], []))
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for i, v in enumerate(test.values):
            c = _cand(v)
            if c is not None and c[0] is not None and c[1]:
                out.append((c[0], c[1], list(test.values[i + 1:])))
    return out


def _rereads(region: Sequence[ast.AST], dotted: str,
             skip_under_locks: bool = True) -> List[ast.AST]:
    """Load-context re-reads of ``dotted`` inside ``region``, skipping
    subtrees under a ``with <lock>:`` (a locked re-read re-validates —
    the registry's canary double-check idiom) and nested defs."""
    hits: List[ast.AST] = []
    stack = list(region)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if skip_under_locks and isinstance(n, (ast.With, ast.AsyncWith)) \
                and any(lock_expr(i.context_expr) is not None
                        for i in n.items):
            continue
        if (isinstance(n, ast.Attribute)
                and isinstance(getattr(n, "ctx", None), ast.Load)
                and dotted_name(n) == dotted):
            hits.append(n)
            continue  # the deref of interest; don't also report `x`
        stack.extend(ast.iter_child_nodes(n))
    return hits


def rule_check_then_deref(ctxs: Sequence[ModuleContext],
                          shared: Optional[Set[str]] = None
                          ) -> List[Finding]:
    """ZL721 (project rule — see the module docstring).

    Receiver scoping: for a ``self.attr`` check the attr must be
    lock-guarded IN THE SAME MODULE (a class whose own module never
    locks around the attribute is single-owner state — the Trainer's
    ``self.state`` must not be condemned because the registry locks an
    unrelated ``dep.state``); checks through other receivers
    (``entry.active`` from the autoscaler) consult the project-wide
    set, because that is exactly the cross-module escape the rule
    exists to catch."""
    # one walk per module: the per-module sets union into the
    # project-wide pool (walking every tree a second time for the
    # union would double the cost of the lint's widest pass)
    local_sets = {ctx.path: collect_shared_attrs([ctx])
                  for ctx in ctxs}
    if shared is None:
        shared = set().union(*local_sets.values()) \
            if local_sets else set()
    findings: List[Finding] = []
    for ctx in ctxs:
        local_shared = local_sets[ctx.path]

        class V(QualnameVisitor):
            def _check(self, test, body, orelse):
                if self.lock_stack:
                    return  # the check holds a lock: writer excluded
                for dotted, guards_body, tail_tests in _none_check(test):
                    recv, attr = dotted.split(".", 1)
                    attr = attr.rsplit(".", 1)[-1]
                    pool = (local_shared if recv == "self" else shared)
                    if attr not in pool:
                        continue
                    region = list(body if guards_body else orelse)
                    region += tail_tests
                    for hit in _rereads(region, dotted):
                        findings.append(Finding(
                            "ZL721", ctx.path, hit.lineno,
                            hit.col_offset, self.qualname,
                            f"{dotted} re-read after its None/"
                            "truthiness check: a concurrent writer "
                            "can null it between the check and this "
                            "deref (it is written under a lock "
                            "elsewhere) — snapshot it into a local "
                            "and check THAT "
                            "(`d = obj.attr` / `if d is not None: "
                            "use d`)"))

            def visit_If(self, node: ast.If):
                self._check(node.test, node.body, node.orelse)
                self.generic_visit(node)

            def visit_IfExp(self, node: ast.IfExp):
                self._check(node.test, [node.body], [node.orelse])
                self.generic_visit(node)

        V(ctx).visit(ctx.tree)
    return findings


# ----------------------------------------------------------------- ZL731
def _lock_owner_map(ctxs: Sequence[ModuleContext]) -> Dict[str, Set[str]]:
    """lock attr name -> {owning classes} from constructor assignments
    (``self._lock = threading.Lock()`` inside ``class X``)."""
    owners: Dict[str, Set[str]] = collections.defaultdict(set)
    for ctx in ctxs:
        class V(QualnameVisitor):
            def visit_Assign(self, node):
                if is_lock_ctor(self.ctx, node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and self.class_stack:
                            owners[t.attr].add(self.class_stack[-1])
                self.generic_visit(node)

        V(ctx).visit(ctx.tree)
    return owners


def rule_lock_order(ctxs: Sequence[ModuleContext]) -> List[Finding]:
    """ZL731 (project rule): build the global lock-acquisition graph
    from lexical ``with`` nesting and flag cycles.  Lock identity is
    ``Class.attr`` — the enclosing class for ``self.x``, the unique
    lock-constructor owner for other receivers, module-scoped
    otherwise (two anonymous ``_lock``s in different files must not
    alias into a false cycle)."""
    owners = _lock_owner_map(ctxs)
    # edge: (src_id, dst_id) -> first acquisition site (path, line, qual)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    for ctx in ctxs:
        class V(QualnameVisitor):
            def __init__(self, c):
                super().__init__(c)
                self.id_stack: List[str] = []

            def _ident(self, lock: str) -> str:
                recv, attr = lock.split(".", 1)
                if recv == "self" and self.class_stack:
                    return f"{self.class_stack[-1]}.{attr}"
                own = owners.get(attr, set())
                if len(own) == 1:
                    return f"{next(iter(own))}.{attr}"
                # ambiguous owner (several classes construct a lock
                # under this attr): fall back to the RECEIVER name,
                # module-scoped — collapsing `a._lock` and `b._lock`
                # into one id would drop the very edges a cross-class
                # cycle is made of, while distinct receiver names keep
                # them apart (name-based, like the hot-path graph)
                return f"{self.ctx.path}::{recv}.{attr}"

            def _visit_with(self, node):
                acquired = []
                for item in node.items:
                    lock = lock_expr(item.context_expr)
                    if lock is None:
                        continue
                    ident = self._ident(lock)
                    for held in self.id_stack:
                        if held != ident:
                            edges.setdefault(
                                (held, ident),
                                (self.ctx.path, node.lineno,
                                 self.qualname))
                    acquired.append(ident)
                    self.id_stack.append(ident)
                    self.lock_stack.append(lock)
                self.generic_visit(node)
                for _ in acquired:
                    self.id_stack.pop()
                    self.lock_stack.pop()

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

        V(ctx).visit(ctx.tree)

    # cycle detection over the edge set
    graph: Dict[str, List[str]] = collections.defaultdict(list)
    for (a, b) in edges:
        graph[a].append(b)
    findings: List[Finding] = []
    reported: Set[Tuple[str, ...]] = set()

    def _dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    cyc = tuple(path)
                    # canonicalize: rotate so the smallest id leads
                    i = cyc.index(min(cyc))
                    canon = cyc[i:] + cyc[:i]
                    if canon in reported:
                        continue
                    reported.add(canon)
                    site_path, line, qual = min(
                        edges[(a, b)] for a, b in
                        zip(canon, canon[1:] + canon[:1]))
                    chain = " -> ".join(canon + (canon[0],))
                    findings.append(Finding(
                        "ZL731", site_path, line, 0, qual,
                        f"lock-order cycle: {chain} — two threads "
                        "taking these locks from opposite ends "
                        "deadlock on each other's second acquisition; "
                        "pick one global order (or merge the locks)"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for start in sorted(graph):
        _dfs(start)
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


# ----------------------------------------------------------------- ZL502
def rule_unbounded_queue(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []

    class V(QualnameVisitor):
        def visit_Call(self, node: ast.Call):
            resolved = self.ctx.resolve(node.func)
            if resolved in ("queue.Queue", "queue.LifoQueue",
                            "queue.PriorityQueue", "Queue"):
                bounded = bool(node.args) or any(
                    kw.arg == "maxsize" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value == 0)
                    for kw in node.keywords)
                if not bounded:
                    findings.append(Finding(
                        "ZL502", self.ctx.path, node.lineno,
                        node.col_offset, self.qualname,
                        "unbounded queue.Queue: under overload it "
                        "converts memory into latency instead of "
                        "shedding — pass maxsize (see "
                        "serving.admission for the argument)"))
            self.generic_visit(node)

    V(ctx).visit(ctx.tree)
    return findings
