"""ZL711 — use-after-donate over the exception-path CFG.

``jax.jit(f, donate_argnums=...)`` transfers buffer ownership into the
executable: after the call, the arrays passed at donated positions are
INVALID — XLA may already have reused their memory as the output (the
whole point: the DecodeEngine's slot-array step updates its
(capacity, heads, max_len, d_head) caches in place instead of copying
them per token).  Reading a donated buffer afterwards is at best a
``RuntimeError: Array has been deleted`` and at worst silent garbage
on a backend that aliased eagerly.  The protocol the decode loop pins
is: every call site REBINDS the donated state from the call's result
in the same statement —

    self._caches, self._tok, self._pos = self._step_fn(
        self._caches, self._tok, self._pos)       # OK: rebound

    out = self._step_fn(self._caches, tok, pos)
    x = self._caches[0]                           # ZL711: poisoned

Mechanics (name-based, like the hot-path call graph):

* a *donating callable* is anything bound from a ``jax.jit``/``pmap``
  call with literal ``donate_argnums`` — directly, or through the
  module call graph: a function whose body (transitively) contains
  such a jit call is a *donating producer*, and names/attributes
  assigned from calls to it inherit the donated positions (this is how
  ``self._step_fn = self._build_step_plan()`` and the
  ``self._admit_fns[bucket]`` plan dict are recognized);
* at a call through a donating callable, the argument expressions at
  donated positions (plain names or ``self.attr`` chains) become
  POISONED;
* any later read of a poisoned name — including passing it to another
  call, which is how the hazard escapes into the call graph — is
  flagged; rebinding it (assignment target, including the same
  statement's tuple target) clears the poison.  The dataflow runs over
  the CFG, so a poison that survives a loop back-edge is caught on the
  next iteration's first read.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .cfg import CFG, build_cfg
from .context import (ModuleContext, binding_targets, dotted_name,
                      header_parts, iter_function_defs, last_name,
                      walk_shallow)
from .dataflow import solve_forward
from .findings import Finding

_JIT_NAMES = ("jax.jit", "jax.pmap")


def _donate_ints(node: ast.AST) -> Iterator[int]:
    """Literal ints of a donate_argnums value, descending through
    tuples/lists AND conditional expressions (``(0, 1) if donate else
    ()`` — the Trainer's gated-donation idiom): may-donate is the
    conservative read."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _donate_ints(e)
    elif isinstance(node, ast.IfExp):
        yield from _donate_ints(node.body)
        yield from _donate_ints(node.orelse)


def _jit_donate_positions(ctx: ModuleContext,
                          node: ast.AST) -> Optional[Set[int]]:
    if not isinstance(node, ast.Call) \
            or ctx.resolve(node.func) not in _JIT_NAMES:
        return None
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            pos = set(_donate_ints(kw.value))
            return pos or None
    return None


def _donating_producers(ctx: ModuleContext) -> Dict[str, Set[int]]:
    """final function name -> donated positions, to a fixpoint over
    the name-based call graph (module docstring)."""
    fns: Dict[str, Tuple[ast.AST, Set[str]]] = {}
    producers: Dict[str, Set[int]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        callees: Set[str] = set()
        for sub in ast.walk(node):
            pos = _jit_donate_positions(ctx, sub)
            if pos:
                producers.setdefault(node.name, set()).update(pos)
            if isinstance(sub, ast.Call):
                name = last_name(sub.func)
                if name:
                    callees.add(name)
        fns[node.name] = (node, callees)
    changed = True
    while changed:
        changed = False
        for name, (_fd, callees) in fns.items():
            for c in callees & set(producers):
                pos = producers[c]
                if not pos <= producers.get(name, set()):
                    producers.setdefault(name, set()).update(pos)
                    changed = True
    return producers


def _value_donates(ctx: ModuleContext, value: ast.AST,
                   producers: Dict[str, Set[int]]) -> Optional[Set[int]]:
    """Donated positions of the callable a value expression builds: a
    literal jit-donate call, a call to a donating producer, or a call
    that THREADS a donating callable through (the decode engine's
    ``self._plan(name, jax.jit(..., donate_argnums=...), specs)`` /
    ``self._plan(name, self._build_admit_fn(b), specs)`` AOT shape —
    the wrapper returns the compiled form of its donating argument, so
    the binding inherits the donated positions)."""
    pos = _jit_donate_positions(ctx, value)
    if pos:
        return pos
    if isinstance(value, ast.Call):
        name = last_name(value.func)
        if name in producers:
            return set(producers[name]) or None
        inherited: Set[int] = set()
        for arg in value.args:
            p = _jit_donate_positions(ctx, arg)
            if not p and isinstance(arg, ast.Call):
                aname = last_name(arg.func)
                if aname in producers:
                    p = producers[aname]
            if p:
                inherited |= p
        if inherited:
            return inherited
    return None


def _attr_donors(ctx: ModuleContext,
                 producers: Dict[str, Set[int]]) -> Dict[str, Set[int]]:
    """Module-wide attribute donors, keyed by the attribute's FINAL
    name: ``self._step_fn`` / ``self._admit_fns[...]`` assigned from a
    donating value anywhere marks every ``<recv>._step_fn`` call a
    donating call — receivers vary across functions (``self`` at the
    binding, a parameter at the call site) but the attribute is the
    protocol, same over-approximation as the hot-path call graph."""
    donors: Dict[str, Set[int]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        pos = _value_donates(ctx, node.value, producers)
        if not pos:
            continue
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute):
                donors.setdefault(t.attr, set()).update(pos)
    return donors


def _callee_key(func: ast.AST) -> Optional[str]:
    """The donor-lookup key of a call's callee: a bare name, a dotted
    attr chain, or the chain of a subscripted plan table
    (``self._stepk_fns[k](...)``)."""
    if isinstance(func, ast.Subscript):
        func = func.value
    return dotted_name(func)


def rule_use_after_donate(ctx: ModuleContext) -> List[Finding]:
    producers = _donating_producers(ctx)
    attr_donors = _attr_donors(ctx, producers)
    # module-level name donors (``step = jax.jit(f, donate_argnums=…)``
    # at top level) are visible to every function in the module
    module_donors: Dict[str, Set[int]] = {}
    for node in walk_shallow(ctx.tree.body):
        if isinstance(node, ast.Assign):
            pos = _value_donates(ctx, node.value, producers)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_donors.setdefault(t.id,
                                                 set()).update(pos)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    for qual, fd in iter_function_defs(ctx):
        # function-local name donors: ``fn = self._admit_fn_for(b)``
        # (a producer call) and ``fn = self._admit_fns[b]`` (a read
        # out of a donating plan table), layered over the module-level
        # bindings
        name_donors: Dict[str, Set[int]] = {
            k: set(v) for k, v in module_donors.items()}
        for node in walk_shallow(fd.body):
            if isinstance(node, ast.Assign):
                pos = _value_donates(ctx, node.value, producers)
                if not pos and isinstance(node.value, ast.Subscript):
                    d = dotted_name(node.value.value)
                    if d:
                        pos = attr_donors.get(d.rsplit(".", 1)[-1])
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            name_donors.setdefault(t.id,
                                                   set()).update(pos)
        if not name_donors and not attr_donors:
            continue
        cfg = build_cfg(fd)

        def _stmt_events(st: ast.stmt):
            """(poison_gens, kills, reads) for one statement."""
            gens: Set[Tuple[str, int]] = set()
            kills: Set[str] = set()
            reads: List[Tuple[str, int]] = []
            for part in header_parts(st):
                for n in walk_shallow([part]):
                    if isinstance(n, ast.Call):
                        # NOTE: calling a *producer* builds a donating
                        # callable — it does not donate its own args;
                        # only calls THROUGH a donor binding poison.
                        # Attr donors match on the attribute tail.
                        key = _callee_key(n.func)
                        pos = None
                        if key is not None:
                            pos = (name_donors.get(key)
                                   if "." not in key else
                                   attr_donors.get(
                                       key.rsplit(".", 1)[-1]))
                        if pos:
                            for p in pos:
                                if p < len(n.args):
                                    d = dotted_name(n.args[p])
                                    if d:
                                        gens.add((d, n.lineno))
                    elif isinstance(n, (ast.Name, ast.Attribute)) \
                            and isinstance(getattr(n, "ctx", None),
                                           ast.Load):
                        d = dotted_name(n)
                        if d:
                            reads.append((d, n.lineno))
            for t in _targets(st):
                d = dotted_name(t)
                if d:
                    kills.add(d)
            return gens, kills, reads

        def transfer(node: int, state, _cfg=cfg):
            st = _cfg.stmts.get(node)
            if st is None:
                return state
            gens, kills, _reads = _stmt_events(st)
            out = {el for el in state if el[0] not in kills}
            out |= {g for g in gens if g[0] not in kills}
            return frozenset(out)

        sol = solve_forward(cfg, transfer)
        for node, st in cfg.stmts.items():
            poisoned = {el[0]: el[1] for el in sol.in_state(node)}
            if not poisoned:
                continue
            _gens, _kills, reads = _stmt_events(st)
            for d, line in reads:
                if d in poisoned and (d, line) not in seen:
                    seen.add((d, line))
                    findings.append(Finding(
                        "ZL711", ctx.path, line, 0, qual,
                        f"read of {d} after it was donated to a "
                        f"donate_argnums executable at line "
                        f"{poisoned[d]}: the buffer now belongs to "
                        "XLA (it may already BE the output) — rebind "
                        "the name from the call's result in the same "
                        "statement, like the DecodeEngine slot-array "
                        "protocol"))
    findings.sort(key=lambda f: (f.line, f.message))
    return findings


def _targets(st: ast.stmt) -> List[ast.AST]:
    out = binding_targets(st)
    if isinstance(st, ast.AugAssign):
        # for poison purposes an augmented write DOES rebind the name
        out.append(st.target)
    return out
