"""A small forward dataflow framework over :mod:`cfg` graphs.

The v2 rules are all forward MAY-analyses over small finite domains
(held resources, poisoned names): union at joins, a per-statement
transfer function, iterate to fixpoint.  The one non-textbook detail is
exception edges: an ``exc`` edge contributes the source node's
**pre**-state, not its post-state — the exception may fire before the
statement's effect lands (``self._sem.acquire()`` that raises never
acquired; a release that raises mid-call may not have released).
Explicit ``raise``/``return``/``break`` edges contribute the
post-state as usual: by the time control transfers, the statement ran.

Usage::

    sol = solve_forward(cfg, transfer)       # transfer(node, in) -> out
    held_at_raise = sol.in_state(cfg.RAISE)

Transfer functions must be monotone over frozensets (only ever derive
``out`` from ``in`` by adding/removing elements based on the statement
alone) — every rule here is gen/kill shaped, so termination is the
standard argument.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet, Tuple

from .cfg import CFG, EXC_KINDS

State = FrozenSet[Tuple]
Transfer = Callable[[int, State], State]

EMPTY: State = frozenset()


class Solution:
    def __init__(self, cfg: CFG, ins: Dict[int, State],
                 outs: Dict[int, State]):
        self.cfg = cfg
        self._ins = ins
        self._outs = outs

    def in_state(self, node: int) -> State:
        return self._ins.get(node, EMPTY)

    def out_state(self, node: int) -> State:
        return self._outs.get(node, EMPTY)


def solve_forward(cfg: CFG, transfer: Transfer,
                  entry_state: State = EMPTY,
                  max_iters: int = 10000) -> Solution:
    """Worklist fixpoint of a forward may-analysis (module doc)."""
    ins: Dict[int, State] = {CFG.ENTRY: entry_state}
    outs: Dict[int, State] = {CFG.ENTRY: entry_state}
    work = [CFG.ENTRY]
    iters = 0
    while work:
        iters += 1
        if iters > max_iters:  # malformed graph guard — never expected
            break
        node = work.pop()
        state = outs.get(node, EMPTY)
        pre = ins.get(node, EMPTY)
        for succ, kind in cfg.succs.get(node, ()):  # propagate
            contrib = pre if kind in EXC_KINDS else state
            old = ins.get(succ)
            new = contrib if old is None else (old | contrib)
            if old is not None and new == old:
                continue
            ins[succ] = new
            outs[succ] = (new if succ in (CFG.EXIT, CFG.RAISE)
                          else transfer(succ, new))
            # re-queue even when the out-state is unchanged: exc edges
            # out of ``succ`` propagate its (just-grown) PRE-state
            work.append(succ)
    return Solution(cfg, ins, outs)
