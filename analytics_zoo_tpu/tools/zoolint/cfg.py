"""Per-function control-flow graphs with explicit exception edges.

The v1 rules were flow-insensitive: they matched patterns anywhere in a
function and could not ask "does every path from this acquire reach a
release?".  The review history of PRs 5-8 shows that is exactly where
the residual bugs live — counters leaked on exception exits, cleanup
present on the normal path and missing on the unwind.  This module
gives the v2 rules (ZL7xx, :mod:`rules_resource` /
:mod:`rules_donation`) a real CFG to run dataflow over.

Model (statement-granular — serving functions are small, blocks buy
nothing):

* one node per AST statement, plus three fixed virtual nodes:
  ``ENTRY`` (0), ``EXIT`` (1, every normal completion: ``return`` and
  falling off the end) and ``RAISE`` (2, every exception that escapes
  the function);
* edges are ``(src, dst, kind)``.  Kinds: ``normal`` (sequencing),
  ``true``/``false`` (branch arms), ``loop`` (back edge),
  ``break``/``continue``, ``return``, ``raise`` (an explicit ``raise``
  statement), ``exc`` (an IMPLICIT exception mid-statement),
  ``reraise`` (a completed ``finally`` resuming a pending exception)
  and ``fallthrough`` (end of body to EXIT);
* inside a protected region (a ``try`` body, its handlers/else under a
  ``finally``) every statement that can plausibly raise gets an ``exc``
  edge to its exception continuation — the handler dispatch, the
  ``finally``, or ``RAISE``.  OUTSIDE any try, implicit exceptions are
  deliberately not modeled (every call can raise in principle; edges
  everywhere would drown the dataflow in paths no cleanup could ever
  have intercepted) — but explicit ``raise`` statements always are.

Exception dispatch: a synthetic ``except-dispatch`` node fans out to
every handler (which handler matches is dynamic), and — unless some
handler is a catch-all (bare ``except`` or ``except BaseException``) —
onward to the outer continuation.  ``except Exception`` is NOT a
catch-all: ``KeyboardInterrupt`` walks straight past it, which is
precisely how the PR 6 ``_acquire`` seat leak happened.

``finally`` is modeled as one shared subgraph (not duplicated per
continuation): every way out of the protected region routes through it,
and its exit edges fan out to each continuation that can actually need
it (the statement after, ``RAISE`` for exception paths, ``EXIT`` for
routed returns, the loop head/exit for routed continue/break).  The
merge is a deliberate over-approximation — a path entering the finally
normally also "sees" the exceptional exit — which for may-analyses adds
at worst a conservative finding, never hides one.

``with`` bodies carry no special exception edges of their own
(``__exit__`` runs transparently); the ``with`` header itself can raise
(``__enter__``) like any other statement when protected.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

#: edge kinds whose dataflow contribution is the PRE-state of the
#: source node (the exception may fire before the statement's effect)
EXC_KINDS = ("exc",)

_NO_RAISE = (ast.Pass, ast.Break, ast.Continue, ast.Global,
             ast.Nonlocal)


class CFG:
    """One function's control-flow graph (see module docstring)."""

    ENTRY = 0
    EXIT = 1
    RAISE = 2

    def __init__(self, fd: ast.AST):
        self.fd = fd
        self.stmts: Dict[int, ast.stmt] = {}
        self.labels: Dict[int, str] = {self.ENTRY: "entry",
                                       self.EXIT: "exit",
                                       self.RAISE: "raise"}
        self.edges: List[Tuple[int, int, str]] = []
        self.succs: Dict[int, List[Tuple[int, str]]] = {}
        self.preds: Dict[int, List[Tuple[int, str]]] = {}
        self._next = 3

    # ---- construction ----
    def new_node(self, stmt: Optional[ast.stmt] = None,
                 label: Optional[str] = None) -> int:
        n = self._next
        self._next += 1
        if stmt is not None:
            self.stmts[n] = stmt
            self.labels[n] = (f"L{stmt.lineno}:"
                              f"{type(stmt).__name__}")
        else:
            self.labels[n] = label or f"synthetic{n}"
        return n

    def add_edge(self, src: int, dst: int, kind: str):
        e = (src, dst, kind)
        if e in self.succs.setdefault(src, []):
            return
        self.edges.append(e)
        self.succs[src].append((dst, kind))
        self.preds.setdefault(dst, []).append((src, kind))

    # ---- introspection (the CFG tests assert on this) ----
    def nodes(self) -> List[int]:
        return sorted(set([self.ENTRY, self.EXIT, self.RAISE])
                      | set(self.labels))

    def describe(self) -> List[Tuple[str, str, str]]:
        """Edges as readable (src_label, dst_label, kind) triples,
        sorted — what the CFG-builder tests assert against."""
        return sorted((self.labels[s], self.labels[d], k)
                      for s, d, k in self.edges)

    def node_at(self, lineno: int) -> Optional[int]:
        """The statement node starting at ``lineno`` (tests)."""
        for n, st in self.stmts.items():
            if st.lineno == lineno:
                return n
        return None


class _FinallyFrame:
    """Bookkeeping for one try-with-finally while its region builds:
    which continuations routed into the shared finally subgraph."""

    __slots__ = ("entry", "needs_exc", "needs_return", "break_frames",
                 "continue_heads", "entered_normally")

    def __init__(self, entry: int):
        self.entry = entry
        self.needs_exc = False
        self.needs_return = False
        self.break_frames: List["_LoopFrame"] = []
        self.continue_heads: List[int] = []
        self.entered_normally = False


class _LoopFrame:
    __slots__ = ("head", "breaks")

    def __init__(self, head: int):
        self.head = head
        self.breaks: List[Tuple[int, str]] = []


class _Ctx:
    """Where control transfers go from the current position."""

    __slots__ = ("exc", "exc_frame", "ret_frame", "loop", "loop_frame")

    def __init__(self, exc: int, exc_frame: Optional[_FinallyFrame],
                 ret_frame: Optional[_FinallyFrame],
                 loop: Optional[_LoopFrame],
                 loop_frame: Optional[_FinallyFrame]):
        self.exc = exc                # exception continuation node
        self.exc_frame = exc_frame    # finally frame exc routes into
        self.ret_frame = ret_frame    # finally frame returns route into
        self.loop = loop              # innermost loop
        self.loop_frame = loop_frame  # finally frame break/continue
        #                               must route through (if any)


def build_cfg(fd: ast.AST) -> CFG:
    """Build the CFG of one ``FunctionDef`` / ``AsyncFunctionDef``.
    Nested function/class definitions are single statements (their
    bodies run elsewhere); analyze them via their own CFGs."""
    cfg = CFG(fd)
    ctx = _Ctx(CFG.RAISE, None, None, None, None)
    exits = _seq(cfg, fd.body, [(CFG.ENTRY, "normal")], ctx)
    for n, _kind in exits:
        cfg.add_edge(n, CFG.EXIT, "fallthrough")
    return cfg


def _seq(cfg: CFG, stmts: Sequence[ast.stmt],
         incoming: List[Tuple[int, str]], ctx: _Ctx
         ) -> List[Tuple[int, str]]:
    cur = incoming
    for st in stmts:
        cur = _stmt(cfg, st, cur, ctx)
    return cur


def _connect(cfg: CFG, incoming: List[Tuple[int, str]], node: int):
    for src, kind in incoming:
        cfg.add_edge(src, node, kind)


def _implicit_exc(cfg: CFG, node: int, st: ast.stmt, ctx: _Ctx):
    """The mid-statement exception edge — only inside protected
    regions, and only for statements that can plausibly raise."""
    if ctx.exc == CFG.RAISE or isinstance(st, _NO_RAISE):
        return
    cfg.add_edge(node, ctx.exc, "exc")
    if ctx.exc_frame is not None:
        ctx.exc_frame.needs_exc = True


def _stmt(cfg: CFG, st: ast.stmt, incoming: List[Tuple[int, str]],
          ctx: _Ctx) -> List[Tuple[int, str]]:
    if isinstance(st, ast.Try):
        return _try(cfg, st, incoming, ctx)
    node = cfg.new_node(st)
    _connect(cfg, incoming, node)
    _implicit_exc(cfg, node, st, ctx)

    if isinstance(st, ast.Return):
        if ctx.ret_frame is not None:
            cfg.add_edge(node, ctx.ret_frame.entry, "return")
            ctx.ret_frame.needs_return = True
        else:
            cfg.add_edge(node, CFG.EXIT, "return")
        return []

    if isinstance(st, ast.Raise):
        cfg.add_edge(node, ctx.exc, "raise")
        if ctx.exc_frame is not None:
            ctx.exc_frame.needs_exc = True
        return []

    if isinstance(st, ast.Break):
        if ctx.loop is None:
            return []
        if ctx.loop_frame is not None:
            cfg.add_edge(node, ctx.loop_frame.entry, "break")
            if ctx.loop not in ctx.loop_frame.break_frames:
                ctx.loop_frame.break_frames.append(ctx.loop)
        else:
            ctx.loop.breaks.append((node, "break"))
        return []

    if isinstance(st, ast.Continue):
        if ctx.loop is None:
            return []
        if ctx.loop_frame is not None:
            cfg.add_edge(node, ctx.loop_frame.entry, "continue")
            if ctx.loop.head not in ctx.loop_frame.continue_heads:
                ctx.loop_frame.continue_heads.append(ctx.loop.head)
        else:
            cfg.add_edge(node, ctx.loop.head, "continue")
        return []

    if isinstance(st, ast.If):
        body_exits = _seq(cfg, st.body, [(node, "true")], ctx)
        if st.orelse:
            else_exits = _seq(cfg, st.orelse, [(node, "false")], ctx)
        else:
            else_exits = [(node, "false")]
        return body_exits + else_exits

    if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
        loop = _LoopFrame(node)
        inner = _Ctx(ctx.exc, ctx.exc_frame, ctx.ret_frame, loop, None)
        body_exits = _seq(cfg, st.body, [(node, "true")], inner)
        for src, _k in body_exits:
            cfg.add_edge(src, node, "loop")
        after: List[Tuple[int, str]] = [(node, "false")]
        if st.orelse:
            after = _seq(cfg, st.orelse, [(node, "false")], ctx)
        return after + loop.breaks

    if isinstance(st, (ast.With, ast.AsyncWith)):
        return _seq(cfg, st.body, [(node, "normal")], ctx)

    # simple statement (incl. nested def/class headers, which execute
    # here as a binding; their bodies do not)
    return [(node, "normal")]


def _catch_all(handlers: Sequence[ast.ExceptHandler]) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        name = h.type
        if isinstance(name, ast.Name) and name.id == "BaseException":
            return True
    return False


def _try(cfg: CFG, st: ast.Try, incoming: List[Tuple[int, str]],
         ctx: _Ctx) -> List[Tuple[int, str]]:
    frame: Optional[_FinallyFrame] = None
    f_entry_node: Optional[int] = None
    if st.finalbody:
        # the finally subgraph is built up-front so the region can
        # route into its entry; its exits are wired at the end
        f_entry_node = cfg.new_node(
            label=f"L{st.lineno}:finally")
        frame = _FinallyFrame(f_entry_node)

    # outer continuations, as seen from inside this try
    outer_exc = frame.entry if frame is not None else ctx.exc
    outer_exc_frame = frame if frame is not None else ctx.exc_frame

    dispatch: Optional[int] = None
    if st.handlers:
        dispatch = cfg.new_node(label=f"L{st.lineno}:except-dispatch")

    body_exc = dispatch if dispatch is not None else outer_exc
    body_exc_frame = (None if dispatch is not None
                      else outer_exc_frame)
    body_ctx = _Ctx(body_exc, body_exc_frame,
                    frame if frame is not None else ctx.ret_frame,
                    ctx.loop,
                    frame if frame is not None else ctx.loop_frame)
    body_exits = _seq(cfg, st.body, incoming, body_ctx)

    # handler bodies and the else clause raise PAST this try's own
    # handlers — to the finally (if any) or the outer continuation
    after_ctx = _Ctx(outer_exc, outer_exc_frame,
                     frame if frame is not None else ctx.ret_frame,
                     ctx.loop,
                     frame if frame is not None else ctx.loop_frame)

    normal_exits: List[Tuple[int, str]] = []
    if st.orelse:
        normal_exits += _seq(cfg, st.orelse, body_exits, after_ctx)
    else:
        normal_exits += body_exits

    if dispatch is not None:
        for h in st.handlers:
            h_exits = _seq(cfg, h.body, [(dispatch, "exc")], after_ctx)
            normal_exits += h_exits
        if not _catch_all(st.handlers):
            # an exception no handler matches keeps propagating
            cfg.add_edge(dispatch, outer_exc, "exc")
            if outer_exc_frame is not None:
                outer_exc_frame.needs_exc = True

    if frame is None:
        return normal_exits

    # ---- wire the shared finally subgraph ----
    if normal_exits:
        frame.entered_normally = True
        _connect(cfg, normal_exits, frame.entry)
    f_exits = _seq(cfg, st.finalbody,
                   [(frame.entry, "normal")], ctx)
    for src, _k in f_exits:
        if frame.needs_exc:
            # the finally RAN to completion before the pending
            # exception resumes — post-state, hence "reraise" (an
            # "exc" edge would wrongly discard the finally's effect,
            # e.g. the release it exists to perform)
            cfg.add_edge(src, ctx.exc, "reraise")
            if ctx.exc_frame is not None:
                ctx.exc_frame.needs_exc = True
        if frame.needs_return:
            if ctx.ret_frame is not None:
                cfg.add_edge(src, ctx.ret_frame.entry, "return")
                ctx.ret_frame.needs_return = True
            else:
                cfg.add_edge(src, CFG.EXIT, "return")
        # break/continue chain through every ENCLOSING finally too (a
        # release in the outer finally must stay visible on the path),
        # exactly like return chains through ctx.ret_frame
        for loop in frame.break_frames:
            if ctx.loop_frame is not None:
                cfg.add_edge(src, ctx.loop_frame.entry, "break")
                if loop not in ctx.loop_frame.break_frames:
                    ctx.loop_frame.break_frames.append(loop)
            else:
                loop.breaks.append((src, "break"))
        for head in frame.continue_heads:
            if ctx.loop_frame is not None:
                cfg.add_edge(src, ctx.loop_frame.entry, "continue")
                if head not in ctx.loop_frame.continue_heads:
                    ctx.loop_frame.continue_heads.append(head)
            else:
                cfg.add_edge(src, head, "continue")
    if frame.entered_normally:
        return f_exits
    return []
