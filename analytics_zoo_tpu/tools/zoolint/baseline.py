"""Baseline: accepted findings, each with a mandatory justification.

The contract (scripts/lint.sh + tests/test_zoolint.py):

* a finding matching a baseline entry on ``(code, path, symbol)`` is
  suppressed — line numbers are deliberately not part of the key, so
  unrelated edits don't invalidate the baseline;
* a NEW finding (no matching entry) fails the run;
* an entry with an empty justification fails the run — the whole point
  is that every accepted violation carries its WHY in review;
* stale entries (matching nothing) are reported so they get pruned, but
  don't fail the run — deleting dead code must not break lint.

Format (JSON, diff-reviewable)::

    {"suppressions": [
        {"code": "ZL301",
         "path": "analytics_zoo_tpu/pipeline/inference/serving.py",
         "symbol": "BucketedExecutableCache._dispatch",
         "justification": "compile-time measurement on the miss path"}
    ]}
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

from .findings import Finding


class BaselineError(ValueError):
    """The baseline file itself is malformed (bad JSON, missing keys,
    empty justification)."""


def load_baseline(path: str) -> List[Dict[str, str]]:
    with open(path, "r", encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: not valid JSON: {e}") from e
    entries = data.get("suppressions")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected a 'suppressions' list")
    for i, e in enumerate(entries):
        for k in ("code", "path", "symbol", "justification"):
            if not isinstance(e.get(k), str):
                raise BaselineError(
                    f"{path}: suppression #{i} missing string {k!r}")
        if not e["justification"].strip():
            raise BaselineError(
                f"{path}: suppression #{i} ({e['code']} {e['path']} "
                f"{e['symbol']}) has an empty justification — accepted "
                "violations must say why")
    return entries


def apply_baseline(findings: Sequence[Finding],
                   entries: Sequence[Dict[str, str]]
                   ) -> Tuple[List[Finding], List[Finding],
                              List[Dict[str, str]]]:
    """Returns (new, suppressed, stale_entries).  An entry suppresses
    every finding with its key — one justified entry covers multiple
    sites in the same symbol (e.g. both branches of a retry)."""
    keys = {(e["code"], e["path"], e["symbol"]) for e in entries}
    new = [f for f in findings if f.key not in keys]
    suppressed = [f for f in findings if f.key in keys]
    hit = {f.key for f in suppressed}
    stale = [e for e in entries
             if (e["code"], e["path"], e["symbol"]) not in hit]
    return new, suppressed, stale


def render_baseline(findings: Sequence[Finding]) -> str:
    """A baseline skeleton for --update-baseline: justifications start
    empty ON PURPOSE — lint fails until a human fills each one in."""
    seen = set()
    entries = []
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({"code": f.code, "path": f.path,
                        "symbol": f.symbol, "justification": ""})
    return json.dumps({"suppressions": entries}, indent=2) + "\n"
