"""The rule catalog behind ``zoolint --explain ZLxxx``.

One entry per rule code: the rationale (WHY the pattern costs), a
minimal bad/good example pair (kept in sync with the fixtures in
``tests/zoolint_fixtures/`` — those are the executable versions), and
the docs anchor.  ``--explain`` is the on-call path: a CI failure
prints a code, and the fix should be one terminal command away.
"""

from __future__ import annotations

from typing import Dict, Optional

_DOC = "docs/dev/zoolint.md"

_FAMILY_ANCHORS = {
    "1": "#zl1xx--recompile-hazards",
    "2": "#zl2xx--tracer-leaks-inside-jit-decorated-scopes",
    "3": "#zl3xx--host-sync-on-the-serving-hot-path-project-wide",
    "4": "#zl4xx--lock-discipline",
    "5": "#zl5xx--thread-lifecycle",
    "6": "#zl6xx--observability-discipline-hot-path-call-graph-based",
    "7": "#zl7xx--exception-path-dataflow-rules-v2",
    "8": "#zl8xx--distributed-contract-rules-v3",
}

CATALOG: Dict[str, Dict[str, str]] = {
    "ZL101": {
        "title": "jax.jit/pmap invoked inside a loop",
        "rationale": "Each iteration builds a fresh wrapper with an "
                     "empty trace cache: one compile per iteration, "
                     "forever.  Hoist the jit out and reuse it.",
        "bad": "for x in xs:\n    f = jax.jit(step)\n    f(x)",
        "good": "f = jax.jit(step)\nfor x in xs:\n    f(x)",
    },
    "ZL102": {
        "title": "immediately-invoked jit",
        "rationale": "`jax.jit(f)(x)` builds a new wrapper per call, "
                     "so every call re-traces.  Bind once, call many.",
        "bad": "out = jax.jit(f)(x)",
        "good": "g = jax.jit(f)\nout = g(x)",
    },
    "ZL103": {
        "title": "unhashable literal in a static jit position",
        "rationale": "Static jit arguments key the compile cache and "
                     "must be hashable — a list raises (or churns the "
                     "cache); a tuple works.",
        "bad": "g = jax.jit(f, static_argnums=(1,))\ng(x, [4, 4])",
        "good": "g = jax.jit(f, static_argnums=(1,))\ng(x, (4, 4))",
    },
    "ZL201": {
        "title": "host cast of a traced value inside jit",
        "rationale": "float()/int()/bool() on a tracer raises "
                     "TracerConversionError at trace time (or "
                     "silently constant-folds).  Use lax primitives "
                     "or hoist the cast out of the jit.",
        "bad": "@jax.jit\ndef f(x):\n    return float(x) * 2",
        "good": "@jax.jit\ndef f(x):\n    return x * 2.0",
    },
    "ZL202": {
        "title": "Python branch on a traced value inside jit",
        "rationale": "Tracers have no truth value — `if x > 0:` fails "
                     "at trace time.  Use lax.cond/jnp.where, or mark "
                     "the argument static.  Shape/ndim/len() tests "
                     "are exempt (static under trace).",
        "bad": "@jax.jit\ndef f(x):\n    if x > 0:\n        return x",
        "good": "@jax.jit\ndef f(x):\n    return jnp.where(x > 0, x, 0)",
    },
    "ZL203": {
        "title": "host materialization of a traced value inside jit",
        "rationale": "np.asarray/.item()/.tolist() force a host "
                     "round-trip inside the trace.  Keep the math in "
                     "jnp until the caller fetches explicitly.",
        "bad": "@jax.jit\ndef f(x):\n    return np.asarray(x).sum()",
        "good": "@jax.jit\ndef f(x):\n    return jnp.sum(x)",
    },
    "ZL301": {
        "title": "block_until_ready on the serving hot path",
        "rationale": "A forced device sync serializes dispatch "
                     "against compute — the exact overlap the "
                     "coalescer pipeline exists to create.  Fetch at "
                     "the fan-out point via jax.device_get; baseline "
                     "with a justification when the sync IS the "
                     "point (compile-time measurement).",
        "bad": "def predict(self, x):\n"
               "    return jax.block_until_ready(self._fn(x))",
        "good": "def predict(self, x):\n"
                "    return jax.device_get(self._fn(x))",
    },
    "ZL302": {
        "title": "implicit device->host materialization on the hot path",
        "rationale": "np.asarray()/float() wrapped straight around a "
                     "dispatch makes the transfer invisible to "
                     "transfer guards and readers.  Fetch via "
                     "jax.device_get.",
        "bad": "rows = np.asarray(self.dispatch_padded(batch))",
        "good": "rows = np.asarray(jax.device_get(\n"
                "    self.dispatch_padded(batch)))",
    },
    "ZL401": {
        "title": "attribute written with AND without its owning lock",
        "rationale": "The lock held at the majority of write sites is "
                     "the owner; a site missing it is a torn/lost "
                     "update one preemption away.  __init__ writes "
                     "are exempt (no concurrent reader exists yet).",
        "bad": "with self._lock:\n    self.n += 1\n...\nself.n = 0",
        "good": "with self._lock:\n    self.n += 1\n...\n"
                "with self._lock:\n    self.n = 0",
    },
    "ZL402": {
        "title": "blocking device work under a held lock",
        "rationale": "warmup/block_until_ready/predict under a lock "
                     "makes every thread contending that lock wait on "
                     "device latency.  Move the dispatch outside the "
                     "critical section.",
        "bad": "with self._lock:\n    out = self._model.predict(x)",
        "good": "with self._lock:\n    model = self._model\n"
                "out = model.predict(x)",
    },
    "ZL501": {
        "title": "non-daemon thread never joined",
        "rationale": "It outlives its owner, pins interpreter exit, "
                     "and strands work on crash.  Pass daemon=True or "
                     "join it in this module.",
        "bad": "threading.Thread(target=loop).start()",
        "good": "threading.Thread(target=loop, daemon=True).start()",
    },
    "ZL502": {
        "title": "unbounded queue.Queue",
        "rationale": "Under overload an unbounded queue converts "
                     "memory into latency instead of shedding — "
                     "request N succeeds seconds too late.  Pass "
                     "maxsize (see serving/admission.py).",
        "bad": "self._q = queue.Queue()",
        "good": "self._q = queue.Queue(maxsize=1024)",
    },
    "ZL601": {
        "title": "print/stdlib logging on the serving hot path",
        "rationale": "Free-text output cannot be joined back to the "
                     "request that produced it, and print takes a "
                     "global I/O lock mid-dispatch.  Use the "
                     "structured logger (observability.log."
                     "get_logger) — its records carry the request id.",
        "bad": "def predict(self, x):\n    print('serving', x.shape)",
        "good": "_slog = get_logger('zoo.serve')\n"
                "def predict(self, x):\n"
                "    _slog.info('serving', shape=x.shape)",
    },
    "ZL701": {
        "title": "acquire() not released on an exception path",
        "rationale": "A resource acquired with recv.acquire() must be "
                     "released on EVERY path out of the function, "
                     "including the unwind: an exception escaping "
                     "between acquire and release leaks the slot "
                     "forever (the caller cannot know it was taken).  "
                     "Returning while holding is allowed — that is "
                     "ownership transfer, and the caller can see it.",
        "bad": "self._sem.acquire()\ntry:\n    return work()\n"
               "finally:\n    pass  # release deleted -> leak",
        "good": "self._sem.acquire()\ntry:\n    return work()\n"
                "finally:\n    self._sem.release()",
    },
    "ZL702": {
        "title": "counter increment not balanced on an exception path",
        "rationale": "A tracked counter (one the module both += and "
                     "-= somewhere: in-flight counts, queue seats, "
                     "slot occupancy) incremented and then leaked on "
                     "an exception exit shrinks capacity one "
                     "exception at a time — the PR 6 _acquire "
                     "KeyboardInterrupt seat leak.  Balance it in an "
                     "except-BaseException unwind before re-raising "
                     "(or hand it to a helper that decrements it).",
        "bad": "self._waiting += 1\nwhile not ready():\n"
               "    if lapsed():\n        raise Timeout()  # seat leaks",
        "good": "self._waiting += 1\ntry:\n    while not ready():\n"
                "        if lapsed():\n            raise Timeout()\n"
                "except BaseException:\n    self._waiting -= 1\n"
                "    raise",
    },
    "ZL711": {
        "title": "use after donate",
        "rationale": "An array passed at a donate_argnums position "
                     "belongs to XLA after the call — its buffer may "
                     "already BE the output.  Reading it is at best "
                     "`Array has been deleted`, at worst silent "
                     "garbage.  Rebind the donated state from the "
                     "call's result in the same statement (the "
                     "DecodeEngine slot-array protocol).",
        "bad": "step = jax.jit(f, donate_argnums=(0,))\n"
               "out = step(caches, tok)\nx = caches[0]  # poisoned",
        "good": "step = jax.jit(f, donate_argnums=(0,))\n"
                "caches, tok = step(caches, tok)",
    },
    "ZL721": {
        "title": "check-then-deref of a shared attribute",
        "rationale": "A None/truthiness check on a shared mutable "
                     "attribute followed by a RE-READ of the same "
                     "attribute races every concurrent writer: the "
                     "attribute can be nulled between the check and "
                     "the deref.  Snapshot into a local and check "
                     "THAT (autoscaler_for reading entry.active "
                     "twice was this bug).",
        "bad": "if entry.active is not None:\n"
               "    return entry.active.version  # may be None now",
        "good": "dep = entry.active\nif dep is not None:\n"
                "    return dep.version",
    },
    "ZL731": {
        "title": "lock-order cycle",
        "rationale": "Two locks acquired in opposite orders at "
                     "different sites deadlock the first time two "
                     "threads interleave the acquisitions under "
                     "load.  Pick one global order (or merge the "
                     "locks).  RLock self-re-entry is exempt.",
        "bad": "def a(self):\n    with self._lock:\n"
               "        with self._cond: ...\n"
               "def b(self):\n    with self._cond:\n"
               "        with self._lock: ...",
        "good": "def a(self):\n    with self._lock:\n"
                "        with self._cond: ...\n"
                "def b(self):\n    with self._lock:\n"
                "        with self._cond: ...",
    },
    "ZL801": {
        "title": "wire op without a peer (or asymmetric codec keys)",
        "rationale": "The router's send sites and the worker's "
                     "dispatch table are the two halves of one "
                     "protocol, usually edited in different files.  "
                     "An op sent with no handler is an unknown-op "
                     "error on the first real call; a handler nothing "
                     "sends is dead surface that rots unseen; a "
                     "decode_X reading a key its encode_X never "
                     "writes is a KeyError on the first real frame.",
        "bad": "conn.send({\"op\": \"flush\", \"id\": rid})\n"
               "# worker: self._control = {\"predict\": ...}  # no flush",
        "good": "conn.send({\"op\": \"flush\", \"id\": rid})\n"
                "# worker: self._control = {\"predict\": ...,\n"
                "#                          \"flush\": self._flush}",
    },
    "ZL802": {
        "title": "error class that cannot round-trip the wire",
        "rationale": "decode_error rebuilds worker exceptions from "
                     "the registry keyed by class name.  A "
                     "ServingError subclass missing from it decodes "
                     "as the bare base — wrong http_status, wrong "
                     "isinstance retry class on the client.  Same "
                     "for a duplicate class name (one wire code, two "
                     "meanings), a missing http_status, or an "
                     "__init__ that cannot absorb cls(msg, **details).",
        "bad": "class WorkerUnavailable(ServingError):\n"
               "    http_status = 503\n"
               "_ERROR_CLASSES = {\"Overloaded\": Overloaded}",
        "good": "_ERROR_CLASSES = {\"Overloaded\": Overloaded,\n"
                "    \"WorkerUnavailable\": WorkerUnavailable}",
    },
    "ZL811": {
        "title": "metric family schema conflict or docs drift",
        "rationale": "The pod aggregator and every dashboard key on "
                     "family name, type, and label schema — a name "
                     "declared as counter here and gauge there "
                     "merges apples into oranges; a *_total gauge "
                     "breaks every rate(); a rank label collides "
                     "with the aggregator's own stamping; a family "
                     "absent from docs/observability.md (or "
                     "documented but never emitted) is operator-"
                     "contract drift.",
        "bad": "Family(\"counter\", \"fx_requests_total\", \"..\")\n"
               "# elsewhere:\n"
               "Family(\"gauge\", \"fx_requests_total\", \"..\")",
        "good": "Family(\"counter\", \"fx_requests_total\", \"..\")\n"
                "# one name, one type, everywhere",
    },
    "ZL812": {
        "title": "ZOO_* env read outside the env contract",
        "rationale": "A knob read wherever os.environ is handy has "
                     "no declaration, no docs row, and no snapshot "
                     "diff when it changes.  Every ZOO_* read goes "
                     "through envcontract.env_str/env_int/env_flag, "
                     "whose VARS table is the single declaration "
                     "point (and must stay documented).",
        "bad": "limit = os.environ.get(\"ZOO_FAKE_LIMIT\")",
        "good": "from analytics_zoo_tpu import envcontract\n"
                "limit = envcontract.env_str(\"ZOO_FAKE_LIMIT\")\n"
                "# + a VARS entry and a docs table row",
    },
    "ZL821": {
        "title": "config read on the compile path, not in the key",
        "rationale": "The executable store replays compiles by "
                     "fingerprint.  A constructor-derived config "
                     "attribute that the compile-reachable path "
                     "reads but the fingerprint never folds means "
                     "two deploys differing only in that knob share "
                     "a key — the second serves the first's STALE "
                     "executable.  Fold the attr (or a canonical "
                     "digest of it) into the fingerprint extras.",
        "bad": "def _shape(self, n):\n"
               "    return n * self._pad_mult  # read, not folded\n"
               "def ensure(self, n):\n"
               "    fp = self.store.fingerprint(\"kind\", self._dg)",
        "good": "fp = self.store.fingerprint(\"kind\", self._dg,\n"
                "                            self._pad_mult)",
    },
}


def anchor_for(code: str) -> str:
    """The docs anchor of a rule code (family-level sections)."""
    digit = code[2] if len(code) > 2 else ""
    return _DOC + _FAMILY_ANCHORS.get(digit, "")


def explain(code: str) -> Optional[str]:
    """The --explain rendering for one code, None when unknown."""
    entry = CATALOG.get(code)
    if entry is None:
        return None
    bad = "\n".join("    " + l for l in entry["bad"].splitlines())
    good = "\n".join("    " + l for l in entry["good"].splitlines())
    return (f"{code} — {entry['title']}\n\n"
            f"{entry['rationale']}\n\n"
            f"bad:\n{bad}\n\n"
            f"good:\n{good}\n\n"
            f"docs: {anchor_for(code)}")
