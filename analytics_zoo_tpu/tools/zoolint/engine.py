"""zoolint engine: walk files, parse once, run every rule.

Module rules see one :class:`ModuleContext`; project rules (the
call-graph hot-path pass) see all of them at once.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

from .context import ModuleContext
from .findings import Finding
from .hotpath import (DEFAULT_HOT_ENTRIES, collect_hot_defs,
                      rule_hot_logging, rule_hot_path)
from .rules_concurrency import (rule_blocking_under_lock,
                                rule_check_then_deref,
                                rule_lock_discipline,
                                rule_lock_order,
                                rule_thread_lifecycle,
                                rule_unbounded_queue)
from .rules_contracts import rule_contracts
from .rules_donation import rule_use_after_donate
from .rules_jax import rule_recompile, rule_tracer_leaks, \
    rule_unhashable_static
from .rules_resource import rule_resource_balance

MODULE_RULES: Tuple[Callable[[ModuleContext], List[Finding]], ...] = (
    rule_recompile,          # ZL101 ZL102
    rule_unhashable_static,  # ZL103
    rule_tracer_leaks,       # ZL201 ZL202 ZL203
    rule_lock_discipline,    # ZL401
    rule_blocking_under_lock,  # ZL402
    rule_thread_lifecycle,   # ZL501
    rule_unbounded_queue,    # ZL502
    rule_resource_balance,   # ZL701 ZL702 (exception-path CFG)
    rule_use_after_donate,   # ZL711 (exception-path CFG)
)

#: every rule code zoolint can emit (docs + fixture tests key off this)
ALL_CODES = ("ZL101", "ZL102", "ZL103", "ZL201", "ZL202", "ZL203",
             "ZL301", "ZL302", "ZL401", "ZL402", "ZL501", "ZL502",
             "ZL601", "ZL701", "ZL702", "ZL711", "ZL721", "ZL731",
             "ZL801", "ZL802", "ZL811", "ZL812", "ZL821")


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               hot_entries: Tuple[str, ...] = DEFAULT_HOT_ENTRIES
               ) -> List[Finding]:
    """Lint files/trees; paths in findings are relative to ``root``
    (default: cwd) with forward slashes, so baselines are portable."""
    root = os.path.abspath(root or os.getcwd())
    ctxs: List[ModuleContext] = []
    findings: List[Finding] = []
    for fp in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fp), root).replace(
            os.sep, "/")
        try:
            with open(fp, "r", encoding="utf-8") as f:
                src = f.read()
            ctx = ModuleContext(rel, src)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "ZL000", rel, getattr(e, "lineno", 1) or 1, 0, "<module>",
                f"file does not parse: {e}"))
            continue
        ctxs.append(ctx)
    for ctx in ctxs:
        for rule in MODULE_RULES:
            findings.extend(rule(ctx))
    # the project-wide call-graph pass is computed ONCE and shared, so
    # every hot-path rule sees the identical "hot" set for free
    hot_defs = collect_hot_defs(ctxs, hot_entries)
    findings.extend(rule_hot_path(ctxs, hot_entries, hot_defs=hot_defs))
    findings.extend(rule_hot_logging(ctxs, hot_entries,
                                     hot_defs=hot_defs))
    # project-wide v2 passes: shared-attr check-then-deref and the
    # global lock-acquisition graph both need every module at once
    findings.extend(rule_check_then_deref(ctxs))
    findings.extend(rule_lock_order(ctxs))
    # v3 distributed-contract pass: one ContractIndex over every
    # module, five ZL8xx families off it (root locates the docs that
    # the drift checks audit against)
    findings.extend(rule_contracts(ctxs, root=root))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
