"""Distributed-contract static rules (zoolint v3).

The v1/v2 rules analyze one function's locks, exceptions, and donation.
This layer checks the AGREEMENTS between modules that make the fleet a
system: a router call site and a worker dispatch table two files away
must name the same ops, an error raised on a worker must survive the
wire envelope back to the client, a metric family must mean the same
thing wherever it is declared, an env knob must exist in exactly one
contract table, and a config attribute that changes compiled output
must rotate the executable-store key.

All five families run off one :class:`ContractIndex` built in a single
pass over every module (the v2 shared-attr-set discipline: walking the
trees once per contract would multiply the lint's widest cost).

Wire op coverage
  ZL801  an op name sent over the fleet wire (``{"op": ...}`` request
         literal) with no worker-side handler — or a handler for an op
         nothing ever sends (dead protocol surface that rots unseen);
         plus encode_X/decode_X symmetry: a key the decoder reads that
         the paired encoder never writes is a KeyError on the first
         real frame.

Error-envelope round-trip
  ZL802  a ServingError subclass that cannot survive
         ``encode_error``/``decode_error``: missing from the wire
         registry (decodes as the bare base — wrong http_status, wrong
         retry class), duplicate class name (code collision: two
         meanings, one wire code), no reachable ``http_status``, or an
         ``__init__`` override that cannot accept
         ``cls(message, **details)``.

Metrics schema
  ZL811  one family name declared with conflicting types or label key
         sets anywhere in the package (the aggregator and dashboards
         key on both), label-name conventions (``rank`` is stamped by
         the pod aggregator, never by a declaring module; model labels
         are ``model``), ``*_total`` names must be counters, and docs
         drift against ``docs/observability.md`` in both directions.

Env contract
  ZL812  an ``os.environ`` read of a ``ZOO_*`` name outside the
         central ``envcontract`` module, an accessor call for a name
         the contract table never declared, or a declared name missing
         from the docs tables.

Fingerprint drift
  ZL821  a constructor-derived config attribute read on the
         compile-reachable path (the call graph from the method that
         calls ``store.fingerprint``) but never folded into the
         fingerprint — the stale-executable bug class: change the
         knob, redeploy, and the store happily serves the OLD
         executable because the key never moved.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .context import ModuleContext, QualnameVisitor, last_name
from .findings import Finding

#: substrings that mark an assignment target as an op dispatch table
_DISPATCH_HINTS = ("control", "dispatch", "handler", "handlers", "ops")
#: metric family names the package owns (docs drift only audits these;
#: fixtures use other prefixes so they never depend on repo docs)
_ZOO_NAME_RE = re.compile(r"^zoo_[a-z0-9_]+$")
#: docs mention of a family: zoo_x_{a,b}_total name alternation and/or
#: a trailing {label,...} block (lookbehind: `analytics_zoo_tpu` must
#: not read as a mention of `zoo_tpu`)
_DOC_TOKEN_RE = re.compile(
    r"(?<![A-Za-z0-9_])zoo_[a-z0-9_{},]*[a-z0-9_}]")
#: label keys a declaring module must not stamp
_LABEL_BANNED = {
    "rank": "the pod aggregator stamps rank on every scraped family — "
            "a module-level rank label double-labels after aggregation",
    "model_name": "the model label convention is 'model'",
    "model_id": "the model label convention is 'model'",
}
#: constructor calls that mark an attribute as runtime state, never
#: key material (ZL821 candidates exclude them)
_STATEFUL_CTORS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
                   "BoundedSemaphore", "Queue", "LifoQueue",
                   "PriorityQueue", "Thread", "deque", "defaultdict",
                   "OrderedDict", "WeakValueDictionary"}
#: attr-name fragments exempt from ZL821: locks/threads are state, and
#: ``*tag*`` is the store-metadata convention (rides the entry header
#: for accounting, deliberately never part of the key — execstore's
#: ``--by-model`` contract)
_EXEMPT_ATTR_HINTS = ("lock", "cond", "thread", "queue", "tag")

_ENV_ACCESSORS = ("env_str", "env_int", "env_flag")


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _read_text(root: Optional[str], rel: str) -> Optional[str]:
    if root is None:
        return None
    p = os.path.join(root, rel)
    try:
        with open(p, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _line_of(text: str, needle: str) -> int:
    for i, line in enumerate(text.splitlines(), start=1):
        if needle in line:
            return i
    return 1


# ===================================================== contract index
class _Site:
    __slots__ = ("path", "line", "col", "symbol")

    def __init__(self, path, line, col, symbol):
        self.path, self.line, self.col = path, line, col
        self.symbol = symbol


class _MetricDecl:
    __slots__ = ("name", "mtype", "label_sets", "site")

    def __init__(self, name, mtype, label_sets, site):
        self.name, self.mtype = name, mtype
        self.label_sets: List[frozenset] = label_sets
        self.site: _Site = site


class _ErrorClass:
    __slots__ = ("name", "bases", "own_http_status", "init_node", "site")

    def __init__(self, name, bases, own_http_status, init_node, site):
        self.name, self.bases = name, bases
        self.own_http_status = own_http_status
        self.init_node = init_node
        self.site = site


class _ModuleScan(QualnameVisitor):
    """One walk per module collecting every contract surface."""

    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.sent_ops: List[Tuple[str, _Site]] = []
        self.handled_ops: List[Tuple[str, _Site]] = []
        self.codec_fns: Dict[str, ast.AST] = {}      # top-level defs
        self.error_classes: List[_ErrorClass] = []
        self.error_registries: List[Tuple[Dict[str, _Site], _Site]] = []
        self.metric_decls: List[_MetricDecl] = []
        self.metric_patterns: List[Tuple[str, str]] = []
        self.env_reads: List[Tuple[ast.AST, _Site]] = []  # key node
        self.env_accessor_calls: List[Tuple[ast.AST, _Site]] = []
        # `op == "x"` compares count as handlers only in functions
        # that bind op FROM AN ENVELOPE (op = req.get("op") /
        # req["op"]) — a TF-graph converter comparing node.op names
        # is not a wire handler
        self._op_compares: List[Tuple[str, str, _Site]] = []
        self._envelope_fns: Set[str] = set()
        self.str_consts: Dict[str, str] = {}          # module level
        self.vars_table: Optional[Dict[str, _Site]] = None
        self.vars_descs: Dict[str, str] = {}
        self._collect_top_level()
        self.visit(ctx.tree)
        for qn, op, site in self._op_compares:
            if qn in self._envelope_fns:
                self.handled_ops.append((op, site))

    @staticmethod
    def _is_op_lookup(value: ast.AST) -> bool:
        """req.get("op") or req["op"] — the envelope-dispatch marker."""
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr == "get" and value.args:
            return _str_const(value.args[0]) == "op"
        if isinstance(value, ast.Subscript):
            return _str_const(value.slice) == "op"
        return False

    # ---- helpers -------------------------------------------------
    def _site(self, node: ast.AST) -> _Site:
        return _Site(self.ctx.path, node.lineno, node.col_offset,
                     self.qualname)

    def _collect_top_level(self):
        for st in self.ctx.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.codec_fns[st.name] = st
            elif isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                v = _str_const(st.value)
                if v is not None:
                    self.str_consts[st.targets[0].id] = v

    def _is_environ(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os")

    # ---- collection ----------------------------------------------
    def visit_Dict(self, node: ast.Dict):
        keys = [_str_const(k) if k is not None else None
                for k in node.keys]
        # sent op: a request envelope literal {"op": "<name>", ...}
        for k, v in zip(node.keys, node.values):
            if _str_const(k) == "op":
                op = _str_const(v)
                if op is not None:
                    self.sent_ops.append((op, self._site(node)))
        # registry_families idiom: {"zoo_x": [...], ...} — every key a
        # metric name (or zoo_-prefixed f-string), every value a list
        if node.keys and all(
                (k is not None
                 and (_str_const(k) is not None
                      or isinstance(k, ast.JoinedStr)))
                for k in node.keys) \
                and all(isinstance(v, ast.List) for v in node.values) \
                and any(s is not None and _ZOO_NAME_RE.match(s)
                        for s in keys):
            for k in node.keys:
                s = _str_const(k)
                if s is not None and _ZOO_NAME_RE.match(s):
                    self.metric_decls.append(_MetricDecl(
                        s, None, [], self._site(k)))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if any(isinstance(t, ast.Name) and t.id == "op"
               for t in node.targets) and self._is_op_lookup(node.value):
            self._envelope_fns.add(self.qualname)
        for t in node.targets:
            tname = (last_name(t) or "").lower()
            if isinstance(node.value, ast.Dict):
                # op dispatch table: {"op-name": handler, ...}
                if any(h in tname for h in _DISPATCH_HINTS) \
                        and node.value.keys and all(
                            _str_const(k) is not None
                            for k in node.value.keys) \
                        and all(isinstance(v, (ast.Name, ast.Attribute))
                                for v in node.value.values):
                    for k in node.value.keys:
                        self.handled_ops.append(
                            (_str_const(k), self._site(k)))
                # error-class wire registry: {"Code": ClassRef, ...}
                if "error_classes" in tname \
                        and node.value.keys and all(
                            _str_const(k) is not None
                            for k in node.value.keys):
                    table = {_str_const(k): self._site(k)
                             for k in node.value.keys}
                    self.error_registries.append(
                        (table, self._site(node)))
                # the env contract table itself
                if isinstance(t, ast.Name) and t.id == "VARS" \
                        and self.ctx.path.endswith("envcontract.py"):
                    self._record_vars(node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        # VARS: Dict[str, str] = {...} — the annotated form
        if isinstance(node.target, ast.Name) \
                and node.target.id == "VARS" \
                and isinstance(node.value, ast.Dict) \
                and self.ctx.path.endswith("envcontract.py"):
            self._record_vars(node.value)
        self.generic_visit(node)

    def _record_vars(self, d: ast.Dict):
        self.vars_table = {
            _str_const(k): self._site(k)
            for k in d.keys if _str_const(k) is not None}
        self.vars_descs = {
            _str_const(k): (_str_const(v) or "")
            for k, v in zip(d.keys, d.values)
            if _str_const(k) is not None}

    def visit_Compare(self, node: ast.Compare):
        # handled op: `op == "x"` / `op in ("a", "b")` (a != / not-in
        # guard rejects an op, it does not handle one)
        if isinstance(node.left, ast.Name) and node.left.id == "op" \
                and len(node.ops) == 1:
            if isinstance(node.ops[0], ast.Eq):
                s = _str_const(node.comparators[0])
                if s is not None:
                    self._op_compares.append(
                        (self.qualname, s, self._site(node)))
            elif isinstance(node.ops[0], ast.In) \
                    and isinstance(node.comparators[0],
                                   (ast.Tuple, ast.List, ast.Set)):
                for e in node.comparators[0].elts:
                    s = _str_const(e)
                    if s is not None:
                        self._op_compares.append(
                            (self.qualname, s, self._site(node)))
        # env membership: "ZOO_X" in os.environ
        if len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and self._is_environ(node.comparators[0]):
            self.env_reads.append((node.left, self._site(node)))
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef):
        bases = [last_name(b) for b in node.bases]
        bases = [b for b in bases if b]
        own_status = any(
            isinstance(st, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "http_status"
                    for t in st.targets)
            for st in node.body)
        init = next((st for st in node.body
                     if isinstance(st, ast.FunctionDef)
                     and st.name == "__init__"), None)
        self.error_classes.append(_ErrorClass(
            node.name, bases, own_status, init, self._site(node)))
        super().visit_ClassDef(node)

    def visit_Call(self, node: ast.Call):
        fn = last_name(node.func)
        # metric family declarations
        if fn == "Family" and len(node.args) >= 2:
            mtype = _str_const(node.args[0])
            name = _str_const(node.args[1])
            if name is not None and mtype is not None:
                self.metric_decls.append(_MetricDecl(
                    name, mtype, self._label_sets(node),
                    self._site(node)))
            elif isinstance(node.args[1], ast.JoinedStr):
                self._pattern(node.args[1])
        elif fn == "summary_family" and node.args:
            name = _str_const(node.args[0])
            if name is not None:
                self.metric_decls.append(_MetricDecl(
                    name, "summary", [], self._site(node)))
            elif isinstance(node.args[0], ast.JoinedStr):
                self._pattern(node.args[0])
        # env reads: os.environ.get/.pop("ZOO_X")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "pop") \
                and self._is_environ(node.func.value) and node.args:
            self.env_reads.append((node.args[0], self._site(node)))
        # envcontract accessor calls (declared-name audit)
        if fn in _ENV_ACCESSORS and node.args:
            self.env_accessor_calls.append(
                (node.args[0], self._site(node)))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if self._is_environ(node.value) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            self.env_reads.append((node.slice, self._site(node)))
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr):
        self._pattern(node)
        self.generic_visit(node)

    def _pattern(self, node: ast.JoinedStr):
        """(prefix, suffix) of a zoo_-prefixed f-string metric name —
        the docs-drift tolerance for families named per-key in a loop
        (``f"zoo_execstore_{k}_total"``)."""
        if not node.values:
            return
        prefix = _str_const(node.values[0])
        if prefix is None or not prefix.startswith("zoo_"):
            return
        suffix = _str_const(node.values[-1]) \
            if len(node.values) > 1 else ""
        self.metric_patterns.append((prefix, suffix or ""))

    def _label_sets(self, call: ast.Call) -> List[frozenset]:
        """Label key sets of one literal Family declaration: every
        all-str-key dict literal inside the samples argument (list
        comprehensions included — ast.walk descends)."""
        out: List[frozenset] = []
        for sub in call.args[2:] + [kw.value for kw in call.keywords]:
            for n in ast.walk(sub):
                if isinstance(n, ast.Dict) and n.keys and all(
                        _str_const(k) is not None for k in n.keys):
                    out.append(frozenset(
                        _str_const(k) for k in n.keys))
        return out


class ContractIndex:
    """Every cross-module contract surface, built once per lint run."""

    def __init__(self, ctxs: Sequence[ModuleContext]):
        self.scans: List[_ModuleScan] = [_ModuleScan(c) for c in ctxs]
        # module-level ZOO_-valued constants, project-wide: the
        # Attribute form of an env read (``flightrec.ENV_DIR``)
        # resolves through this map when the name is unambiguous
        self.zoo_constants: Dict[str, Set[str]] = {}
        for sc in self.scans:
            for name, val in sc.str_consts.items():
                if val.startswith("ZOO_"):
                    self.zoo_constants.setdefault(name, set()).add(val)
        self.env_vars: Optional[Dict[str, _Site]] = None
        self.env_descs: Dict[str, str] = {}
        self.envcontract_path: Optional[str] = None
        for sc in self.scans:
            if sc.vars_table is not None:
                self.env_vars = sc.vars_table
                self.env_descs = sc.vars_descs
                self.envcontract_path = sc.ctx.path
        # op tables (first site wins for reporting)
        self.sent_ops: Dict[str, _Site] = {}
        self.handled_ops: Dict[str, _Site] = {}
        for sc in self.scans:
            for op, site in sc.sent_ops:
                self.sent_ops.setdefault(op, site)
            for op, site in sc.handled_ops:
                self.handled_ops.setdefault(op, site)
        # metric families, merged by name
        self.metric_decls: Dict[str, List[_MetricDecl]] = {}
        self.metric_patterns: List[Tuple[str, str]] = []
        for sc in self.scans:
            for d in sc.metric_decls:
                self.metric_decls.setdefault(d.name, []).append(d)
            self.metric_patterns.extend(sc.metric_patterns)

    def resolve_env_name(self, sc: _ModuleScan,
                         node: ast.AST) -> Optional[str]:
        """The concrete env-var name of a read's key expression:
        string literal, module-level constant, or a cross-module
        ``mod.ENV_X`` attribute when exactly one module declares it."""
        s = _str_const(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name):
            return sc.str_consts.get(node.id)
        if isinstance(node, ast.Attribute):
            vals = self.zoo_constants.get(node.attr, set())
            if len(vals) == 1:
                return next(iter(vals))
        return None

    # ---- snapshot ------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The committed-contract rendering (``zoolint contracts``):
        pure data, deterministically ordered, diffable in review."""
        errors: Dict[str, int] = {}
        for name, status in self._error_statuses().items():
            if status is not None:
                errors[name] = status
        metrics: Dict[str, Dict[str, Any]] = {}
        for name, decls in sorted(self.metric_decls.items()):
            mtype = next((d.mtype for d in decls
                          if d.mtype is not None), None)
            labels: Set[str] = set()
            for d in decls:
                for ls in d.label_sets:
                    labels |= ls
            metrics[name] = {"type": mtype or "unknown",
                             "labels": sorted(labels)}
        return {
            "ops": {"sent": sorted(self.sent_ops),
                    "handled": sorted(self.handled_ops)},
            "errors": dict(sorted(errors.items())),
            "env": {name: self.env_descs.get(name, "")
                    for name in sorted(self.env_vars or ())},
            "metrics": metrics,
        }

    def _error_statuses(self) -> Dict[str, Optional[int]]:
        """class name -> effective http_status through the in-index
        base chain (None when unreachable)."""
        classes: Dict[str, _ErrorClass] = {}
        for sc in self.scans:
            for ec in sc.error_classes:
                classes.setdefault(ec.name, ec)

        own: Dict[str, Optional[int]] = {}
        for sc in self.scans:
            for ec in sc.error_classes:
                if ec.own_http_status:
                    own.setdefault(ec.name, self._status_value(sc, ec))

        def status(name: str, seen: Set[str]) -> Optional[int]:
            if name in seen or name not in classes:
                return None
            seen.add(name)
            if name in own:
                return own[name]
            for b in classes[name].bases:
                s = status(b, seen)
                if s is not None:
                    return s
            return None

        out: Dict[str, Optional[int]] = {}
        for name, ec in classes.items():
            if self._is_serving_error(name, classes):
                out[name] = status(name, set())
        return out

    @staticmethod
    def _is_serving_error(name: str,
                          classes: Dict[str, _ErrorClass]) -> bool:
        seen: Set[str] = set()
        stack = [name]
        while stack:
            n = stack.pop()
            if n == "ServingError":
                return True
            if n in seen or n not in classes:
                continue
            seen.add(n)
            stack.extend(classes[n].bases)
        return False

    def _status_value(self, sc: _ModuleScan,
                      ec: _ErrorClass) -> Optional[int]:
        # re-find the class node to read the literal status value
        for node in ast.walk(sc.ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == ec.name:
                for st in node.body:
                    if isinstance(st, ast.Assign) and any(
                            isinstance(t, ast.Name)
                            and t.id == "http_status"
                            for t in st.targets) \
                            and isinstance(st.value, ast.Constant) \
                            and isinstance(st.value.value, int):
                        return st.value.value
        return None


# ========================================================== ZL801
def rule_wire_ops(index: ContractIndex) -> List[Finding]:
    findings: List[Finding] = []
    sent, handled = index.sent_ops, index.handled_ops
    # coverage runs only when the linted set contains BOTH sides of
    # the protocol — linting the router alone must not condemn every
    # send for lacking a handler it cannot see
    if sent and handled:
        for op in sorted(set(sent) - set(handled)):
            s = sent[op]
            findings.append(Finding(
                "ZL801", s.path, s.line, s.col, s.symbol,
                f"wire op {op!r} is sent but no dispatch-table entry "
                "or `op == ...` handler exists anywhere in the linted "
                "set — the worker replies with an unknown-op error on "
                "the first real call"))
        for op in sorted(set(handled) - set(sent)):
            s = handled[op]
            findings.append(Finding(
                "ZL801", s.path, s.line, s.col, s.symbol,
                f"wire op {op!r} has a handler but nothing ever sends "
                "it — dead protocol surface: either wire up the "
                "caller or delete the handler before it rots"))
    # encode_X/decode_X key symmetry, per module
    for sc in index.scans:
        for name, fn in sorted(sc.codec_fns.items()):
            if not name.startswith("decode_"):
                continue
            enc = sc.codec_fns.get("encode_" + name[len("decode_"):])
            if enc is None:
                continue
            written = _written_keys(enc, sc.codec_fns)
            read = _read_keys(fn, sc.codec_fns)
            if not written or not read:
                continue  # opaque codec (no literal keys on one side)
            missing = sorted(read - written)
            if missing:
                findings.append(Finding(
                    "ZL801", sc.ctx.path, fn.lineno, fn.col_offset,
                    name,
                    f"{name}() reads key(s) {missing} that its paired "
                    f"encoder never writes — a KeyError on the first "
                    "frame a real peer produces"))
    return findings


def _written_keys(fn: ast.AST,
                  module_fns: Dict[str, ast.AST]) -> Set[str]:
    """String dict keys an encoder produces, following one level of
    module-local helper calls (``encode_binary`` delegates its header
    layout to ``_binary_parts``)."""
    out: Set[str] = set()
    for body in _with_called_bodies(fn, module_fns):
        for n in ast.walk(body):
            if isinstance(n, ast.Dict):
                for k in n.keys:
                    s = _str_const(k) if k is not None else None
                    if s is not None:
                        out.add(s)
            elif isinstance(n, ast.Subscript) \
                    and isinstance(getattr(n, "ctx", None), ast.Store):
                s = _str_const(n.slice)
                if s is not None:
                    out.add(s)
    return out


def _read_keys(fn: ast.AST,
               module_fns: Dict[str, ast.AST]) -> Set[str]:
    out: Set[str] = set()
    for body in _with_called_bodies(fn, module_fns):
        for n in ast.walk(body):
            if isinstance(n, ast.Subscript) \
                    and isinstance(getattr(n, "ctx", None), ast.Load):
                s = _str_const(n.slice)
                if s is not None:
                    out.add(s)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("get", "pop") and n.args:
                s = _str_const(n.args[0])
                if s is not None:
                    out.add(s)
            elif isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.In, ast.NotIn)):
                s = _str_const(n.left)
                if s is not None:
                    out.add(s)
    return out


def _with_called_bodies(fn: ast.AST,
                        module_fns: Dict[str, ast.AST]
                        ) -> List[ast.AST]:
    bodies = [fn]
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in module_fns \
                and module_fns[n.func.id] is not fn:
            bodies.append(module_fns[n.func.id])
    return bodies


# ========================================================== ZL802
def rule_error_envelope(index: ContractIndex) -> List[Finding]:
    findings: List[Finding] = []
    classes: Dict[str, List[Tuple[_ModuleScan, _ErrorClass]]] = {}
    by_name: Dict[str, _ErrorClass] = {}
    for sc in index.scans:
        for ec in sc.error_classes:
            classes.setdefault(ec.name, []).append((sc, ec))
            by_name.setdefault(ec.name, ec)
    serving = {name for name in classes
               if ContractIndex._is_serving_error(name, by_name)}
    if not serving:
        return findings
    registries = [table for sc in index.scans
                  for table, _ in sc.error_registries]
    registered: Set[str] = set()
    for table in registries:
        registered |= set(table)
    statuses = index._error_statuses()
    for name in sorted(serving):
        decls = classes[name]
        # code collision: ``code`` IS the class name on the wire —
        # two definitions decode to whichever one the registry holds
        if len(decls) > 1:
            for sc, ec in decls:
                findings.append(Finding(
                    "ZL802", ec.site.path, ec.site.line, ec.site.col,
                    name,
                    f"error class {name} is defined in more than one "
                    "module: the wire code is the class name, so the "
                    "registry can only round-trip one of them — "
                    "rename or consolidate"))
        sc, ec = decls[0]
        if registries and name not in registered:
            findings.append(Finding(
                "ZL802", ec.site.path, ec.site.line, ec.site.col, name,
                f"ServingError subclass {name} is missing from the "
                "wire error registry (_ERROR_CLASSES): it decodes as "
                "the bare base class — wrong http_status and wrong "
                "retry semantics on the client"))
        if statuses.get(name) is None:
            findings.append(Finding(
                "ZL802", ec.site.path, ec.site.line, ec.site.col, name,
                f"error class {name} has no reachable http_status "
                "(own or inherited within the linted set) — a web "
                "frontend cannot map it without string-matching"))
        if ec.init_node is not None:
            bad = _init_cannot_roundtrip(ec.init_node)
            if bad:
                findings.append(Finding(
                    "ZL802", ec.site.path, ec.init_node.lineno,
                    ec.init_node.col_offset, name,
                    f"{name}.__init__ cannot be called as "
                    f"cls(message, **details) ({bad}) — decode_error "
                    "raises TypeError instead of the reconstructed "
                    "exception"))
    return findings


def _init_cannot_roundtrip(init: ast.FunctionDef) -> Optional[str]:
    a = init.args
    if a.kwarg is None:
        return "no **kwargs to absorb arbitrary detail fields"
    positional = a.posonlyargs + a.args
    required = len(positional) - len(a.defaults)
    if required > 2:  # self + message
        names = [p.arg for p in positional[2:required]]
        return f"required positional parameter(s) {names} beyond message"
    return None


# ========================================================== ZL811
def rule_metrics_schema(index: ContractIndex,
                        root: Optional[str]) -> List[Finding]:
    findings: List[Finding] = []
    for name, decls in sorted(index.metric_decls.items()):
        types = {d.mtype for d in decls if d.mtype is not None}
        if len(types) > 1:
            for d in decls:
                if d.mtype is not None:
                    findings.append(Finding(
                        "ZL811", d.site.path, d.site.line, d.site.col,
                        d.site.symbol,
                        f"metric family {name!r} declared as "
                        f"{d.mtype!r} here but also as "
                        f"{sorted(types - {d.mtype})} elsewhere — one "
                        "name, one type, or the aggregator merges "
                        "apples into oranges"))
        if name.endswith("_total"):
            for d in decls:
                if d.mtype not in (None, "counter"):
                    findings.append(Finding(
                        "ZL811", d.site.path, d.site.line, d.site.col,
                        d.site.symbol,
                        f"{name!r} is declared as a {d.mtype} — the "
                        "*_total suffix promises a monotonic counter "
                        "to every PromQL rate() over it"))
        label_sets = {ls for d in decls for ls in d.label_sets if ls}
        if len(label_sets) > 1:
            d = decls[-1]
            findings.append(Finding(
                "ZL811", d.site.path, d.site.line, d.site.col,
                d.site.symbol,
                f"metric family {name!r} is declared with conflicting "
                f"label sets {sorted(sorted(ls) for ls in label_sets)}"
                " — series of one family must share one label schema"))
        for d in decls:
            for ls in d.label_sets:
                for key in sorted(ls & set(_LABEL_BANNED)):
                    findings.append(Finding(
                        "ZL811", d.site.path, d.site.line, d.site.col,
                        d.site.symbol,
                        f"label key {key!r} on {name!r}: "
                        f"{_LABEL_BANNED[key]}"))
    findings.extend(_docs_drift(index, root))
    return findings


def _expand_doc_tokens(text: str) -> Set[str]:
    """Every concrete family name the docs mention.

    Two brace idioms coexist in the docs: a MID-name group is
    alternation (``zoo_x_{a,b}_total`` -> zoo_x_a_total,
    zoo_x_b_total) and a TERMINAL (or unclosed, e.g. truncated at a
    ``=``) group is a Prometheus label block
    (``zoo_shed_total{model,class}``) — the name stops before it."""
    out: Set[str] = set()
    for tok in _DOC_TOKEN_RE.findall(text):
        variants = [""]
        i = 0
        while i < len(tok):
            c = tok[i]
            if c == "{":
                j = tok.find("}", i)
                if j < 0 or j == len(tok) - 1:
                    break  # label block: the family name is complete
                parts = tok[i + 1:j].split(",")
                variants = [v + p for v in variants for p in parts]
                i = j + 1
            else:
                variants = [v + c for v in variants]
                i += 1
        for v in variants:
            if _ZOO_NAME_RE.match(v):
                out.add(v)
    return out


def _docs_drift(index: ContractIndex,
                root: Optional[str]) -> List[Finding]:
    findings: List[Finding] = []
    doc_rel = "docs/observability.md"
    text = _read_text(root, doc_rel)
    zoo_decls = {n: ds for n, ds in index.metric_decls.items()
                 if _ZOO_NAME_RE.match(n)}
    # both directions gate on the docs existing AND the linted set
    # actually declaring zoo_ families — a fixture linted alone (or a
    # docs-less checkout) must not fabricate drift
    if text is None or not zoo_decls:
        return findings
    documented = _expand_doc_tokens(text)
    for name, decls in sorted(zoo_decls.items()):
        if name in documented or name in text:
            continue
        d = decls[0]
        findings.append(Finding(
            "ZL811", d.site.path, d.site.line, d.site.col,
            d.site.symbol,
            f"metric family {name!r} is emitted here but absent from "
            f"{doc_rel} — every family is part of the operator "
            "contract; add its table row"))
    emitted = set(index.metric_decls)
    summaries = {n for n, ds in index.metric_decls.items()
                 if any(d.mtype == "summary" for d in ds)}
    for tok in sorted(documented):
        if tok in emitted:
            continue
        if any(tok == s + suf for s in summaries
               for suf in ("_sum", "_count")):
            continue  # summary families render _sum/_count series
        if any(tok.startswith(p) and tok.endswith(s)
               and len(tok) > len(p) + len(s)
               for p, s in index.metric_patterns):
            continue  # per-key f-string family (zoo_execstore_*_total)
        findings.append(Finding(
            "ZL811", doc_rel, _line_of(text, tok), 0, "<docs>",
            f"{doc_rel} documents metric family {tok!r} but nothing "
            "in the linted set declares it — stale docs row (or a "
            "family that silently vanished in a refactor)"))
    return findings


# ========================================================== ZL812
def rule_env_contract(index: ContractIndex,
                      root: Optional[str]) -> List[Finding]:
    findings: List[Finding] = []
    for sc in index.scans:
        if sc.ctx.path.endswith("envcontract.py"):
            continue  # the contract module's own reads are the point
        for keynode, site in sc.env_reads:
            name = index.resolve_env_name(sc, keynode)
            if name is not None and name.startswith("ZOO_"):
                findings.append(Finding(
                    "ZL812", site.path, site.line, site.col,
                    site.symbol,
                    f"os.environ read of {name!r} outside the central "
                    "envcontract module — route it through "
                    "envcontract.env_str/env_int/env_flag so the knob "
                    "is declared, documented, and snapshot-diffed"))
    if index.env_vars is not None:
        declared = set(index.env_vars)
        for sc in index.scans:
            for keynode, site in sc.env_accessor_calls:
                name = index.resolve_env_name(sc, keynode)
                if name is not None and name.startswith("ZOO_") \
                        and name not in declared:
                    findings.append(Finding(
                        "ZL812", site.path, site.line, site.col,
                        site.symbol,
                        f"envcontract accessor called with {name!r} "
                        "which VARS never declares — the call raises "
                        "KeyError at runtime; add the table entry"))
        docs = [(rel, _read_text(root, rel))
                for rel in ("docs/serving.md",
                            "docs/distributed-training.md")]
        texts = [t for _, t in docs if t is not None]
        if texts:
            for name in sorted(declared):
                if not any(name in t for t in texts):
                    site = index.env_vars[name]
                    findings.append(Finding(
                        "ZL812", site.path, site.line, site.col,
                        "VARS",
                        f"declared env var {name!r} appears in no "
                        "docs env table (docs/serving.md / "
                        "docs/distributed-training.md) — an "
                        "undocumented knob is an unusable knob"))
    return findings


# ========================================================== ZL821
def rule_fingerprint_drift(ctxs: Sequence[ModuleContext]
                           ) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_class_fp_drift(ctx, node))
    return findings


def _self_attr(n: ast.AST) -> Optional[str]:
    if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
            and n.value.id == "self":
        return n.attr
    return None


def _self_call_names(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            a = _self_attr(n.func)
            if a is not None:
                out.add(a)
    return out


def _local_flow(fn: ast.AST) -> Dict[str, Set[str]]:
    """local name -> self-attr names its value (transitively) derives
    from; a few fixpoint passes stand in for real ordering."""
    deps: Dict[str, Set[str]] = {}

    def refs(expr: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(expr):
            a = _self_attr(n)
            if a is not None:
                out.add(a)
            elif isinstance(n, ast.Name) and n.id in deps:
                out |= deps[n.id]
        return out

    for _ in range(3):
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                r = refs(n.value)
                for t in n.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            deps[leaf.id] = deps.get(leaf.id,
                                                     set()) | r
            elif isinstance(n, ast.AugAssign) \
                    and isinstance(n.target, ast.Name):
                deps[n.target.id] = deps.get(n.target.id,
                                             set()) | refs(n.value)
    return deps


def _attrs_reached(expr: ast.AST, flow: Dict[str, Set[str]],
                   methods: Dict[str, ast.AST],
                   visited: Set[str]) -> Set[str]:
    """self-attrs an expression's value derives from: direct reads,
    locals (via ``flow``), and the full bodies of self-methods it
    calls (transitively — ``self._fp_parts()`` folds whatever the
    override reads)."""
    out: Set[str] = set()
    for n in ast.walk(expr):
        a = _self_attr(n)
        if a is not None:
            out.add(a)
        elif isinstance(n, ast.Name) and n.id in flow:
            out |= flow[n.id]
    for m in _self_call_names(expr):
        out |= _method_attr_closure(m, methods, visited)
    return out


def _method_attr_closure(name: str, methods: Dict[str, ast.AST],
                         visited: Set[str]) -> Set[str]:
    if name in visited or name not in methods:
        return set()
    visited.add(name)
    fn = methods[name]
    out: Set[str] = set()
    for n in ast.walk(fn):
        a = _self_attr(n)
        if a is not None:
            out.add(a)
    for m in _self_call_names(fn):
        out |= _method_attr_closure(m, methods, visited)
    return out


def _class_fp_drift(ctx: ModuleContext,
                    cls: ast.ClassDef) -> List[Finding]:
    methods: Dict[str, ast.AST] = {
        st.name: st for st in cls.body
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))}
    # the fingerprinting method(s): whoever calls *.fingerprint(...)
    fp_calls: List[Tuple[str, ast.Call]] = []
    for mname, fn in methods.items():
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "fingerprint":
                fp_calls.append((mname, n))
    if not fp_calls:
        return []
    init = methods.get("__init__")
    if init is None:
        return []

    # ---- candidates: __init__ config attrs derived from ctor params
    params = {a.arg for a in init.args.args
              + init.args.posonlyargs + init.args.kwonlyargs
              if a.arg != "self"}
    init_flow = _param_flow(init, params)
    candidates: Dict[str, ast.AST] = {}      # attr -> RHS expr
    attr_rhs: Dict[str, ast.AST] = {}
    lineage: Dict[str, Set[str]] = {}        # attr -> ctor params
    for n in ast.walk(init):
        if isinstance(n, ast.Assign) and len(n.targets) >= 1:
            for t in n.targets:
                a = _self_attr(t)
                if a is None:
                    continue
                attr_rhs.setdefault(a, n.value)
                lin: Set[str] = set()
                for leaf in ast.walk(n.value):
                    if isinstance(leaf, ast.Name):
                        lin |= init_flow.get(leaf.id, set())
                lineage[a] = lineage.get(a, set()) | lin
                if any(h in a.lower() for h in _EXEMPT_ATTR_HINTS):
                    continue
                if isinstance(n.value, ast.Call) \
                        and last_name(n.value.func) in _STATEFUL_CTORS:
                    continue
                if lin:
                    candidates.setdefault(a, n.value)
    if not candidates:
        return []

    # ---- folded: attrs whose value reaches the fingerprint args
    folded: Set[str] = set()
    receivers: Set[str] = set()
    for mname, call in fp_calls:
        flow = _local_flow(methods[mname])
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            folded |= _attrs_reached(arg, flow, methods, set())
        receivers |= _attrs_reached(call.func.value, flow, methods,
                                    set())
    # __init__-RHS closure: a folded derived attr folds whatever its
    # construction read (``_wdigest = digest(placed)`` folds the
    # weights; ``_jit = self._make_jit(...)`` folds ``_fn``)
    changed = True
    while changed:
        changed = False
        for a in sorted(folded):
            rhs = attr_rhs.get(a)
            if rhs is None:
                continue
            more = _attrs_reached(rhs, _local_flow(init), methods,
                                  set())
            if not more <= folded:
                folded |= more
                changed = True
    # shared-lineage exemption: when a folded attr is DERIVED from the
    # same ctor params as a candidate, the candidate's value is already
    # keyed by proxy — the fold-the-canonical-digest idiom
    # (``_mesh_cfg = canonical(spec); _mesh_spec = spec`` folds the
    # digest, which covers the spec)
    folded_lineage: Set[str] = set()
    for a in folded:
        folded_lineage |= lineage.get(a, set())
    for a, lin in lineage.items():
        if lin and lin <= folded_lineage:
            folded.add(a)

    # ---- compile-reachable closure from the fingerprint method(s)
    reach: Set[str] = set()
    stack = [m for m, _ in fp_calls]
    while stack:
        m = stack.pop()
        if m in reach or m not in methods:
            continue
        reach.add(m)
        stack.extend(_self_call_names(methods[m]))

    findings: List[Finding] = []
    reported: Set[str] = set()
    for mname in sorted(reach):
        fn = methods[mname]
        flow = _local_flow(fn)
        service = _service_attrs(fn, flow)
        parents = {child: parent for parent in ast.walk(fn)
                   for child in ast.iter_child_nodes(parent)}
        for n in ast.walk(fn):
            a = _self_attr(n)
            if a is None or a in reported:
                continue
            if a not in candidates or a in folded or a in receivers \
                    or a in service:
                continue
            if not isinstance(getattr(n, "ctx", None), ast.Load):
                continue
            par = parents.get(n)
            if isinstance(par, ast.Attribute) or (
                    isinstance(par, ast.Call) and par.func is n):
                continue  # self.attr.method(...): a service, not a key
            reported.add(a)
            findings.append(Finding(
                "ZL821", ctx.path, n.lineno, n.col_offset,
                f"{cls.name}.{mname}",
                f"config attribute self.{a} (constructor-derived) is "
                "read on the compile-reachable path but never folded "
                "into the store fingerprint — two deploys differing "
                "only in this knob share a key, and the second one "
                "serves the first one's STALE executable; add it to "
                "the fingerprint extras (_fp_parts or the fingerprint "
                "call)"))
    return findings


def _param_flow(init: ast.AST, params: Set[str]) -> Dict[str, Set[str]]:
    """local -> ctor params it derives from (inside __init__)."""
    deps: Dict[str, Set[str]] = {p: {p} for p in params}
    for _ in range(3):
        for n in ast.walk(init):
            if isinstance(n, ast.Assign):
                refs: Set[str] = set()
                for leaf in ast.walk(n.value):
                    if isinstance(leaf, ast.Name) and leaf.id in deps:
                        refs |= deps[leaf.id]
                for t in n.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name) \
                                and leaf.id not in params:
                            deps[leaf.id] = deps.get(leaf.id,
                                                     set()) | refs
    return deps


def _param_refs(expr: ast.AST, params: Set[str],
                flow: Dict[str, Set[str]]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and flow.get(n.id):
            return True
    return False


def _service_attrs(fn: ast.AST, flow: Dict[str, Set[str]]) -> Set[str]:
    """Attrs read only to be USED as an object (receiver of a method
    call, directly or through a local) — services, not key material."""
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute):
            recv = n.func.value
            a = _self_attr(recv)
            if a is not None:
                out.add(a)
            elif isinstance(recv, ast.Name) and recv.id in flow:
                out |= flow[recv.id]
    return out


# ===================================================== engine entry
def rule_contracts(ctxs: Sequence[ModuleContext],
                   root: Optional[str] = None,
                   index: Optional[ContractIndex] = None
                   ) -> List[Finding]:
    """All five ZL8xx families off one shared index (engine hook)."""
    if index is None:
        index = ContractIndex(ctxs)
    findings: List[Finding] = []
    findings.extend(rule_wire_ops(index))
    findings.extend(rule_error_envelope(index))
    findings.extend(rule_metrics_schema(index, root))
    findings.extend(rule_env_contract(index, root))
    findings.extend(rule_fingerprint_drift(ctxs))
    return findings
