"""Shared AST infrastructure for zoolint rules.

One parse per file; rules receive a :class:`ModuleContext` carrying the
tree plus resolved import aliases (``jax``/``numpy``/``threading``/
``queue`` under whatever names the module bound them), a dotted-name
resolver, and a qualname-tracking walker base.  Everything here is
stdlib-only — the static half of zoolint must never import jax.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

# attribute reads that are static under a jax trace (never materialize
# a tracer) — branching or casting on these is fine inside jit
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
# calls whose result is static / host-side even with traced arguments
STATIC_CALLS = {"len", "isinstance", "issubclass", "hasattr", "callable",
                "getattr", "type", "id", "repr", "str", "format"}
# lock-ish attribute names: `with recv.<attr>:` acquires a mutex.
# Semaphores are deliberately NOT matched — they bound concurrency, they
# don't own data.
_LOCK_NAME_HINTS = ("lock", "cond", "mutex")
_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_name(node: ast.AST) -> Optional[str]:
    """The final component of a call target: ``x.y.predict`` -> "predict"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_shallow(nodes: Sequence[ast.AST],
                 skip=(ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda)) -> Iterator[ast.AST]:
    """ast.walk over statements WITHOUT descending into nested function
    bodies (their code runs later, not here).  Decorators and default
    expressions of nested defs DO execute here, so they are yielded."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, skip):
            for dec in getattr(node, "decorator_list", []):
                stack.append(dec)
            args = getattr(node, "args", None)
            if isinstance(args, ast.arguments):
                stack.extend(args.defaults)
                stack.extend(d for d in args.kw_defaults if d is not None)
            continue
        stack.extend(ast.iter_child_nodes(node))


class ModuleContext:
    """One parsed module + its import-alias table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # alias -> canonical module ("jax", "numpy", ...)
        self.module_aliases: Dict[str, str] = {}
        # local name -> canonical dotted name ("jit" -> "jax.jit")
        self.name_aliases: Dict[str, str] = {}
        self._scan_imports()

    def _scan_imports(self):
        canon = {"jax": "jax", "numpy": "numpy", "threading": "threading",
                 "queue": "queue", "functools": "functools",
                 "jax.numpy": "jax.numpy"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in canon:
                        self.module_aliases[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if node.module in canon:
                        self.name_aliases[a.asname or a.name] = \
                            f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression: ``j.jit`` -> "jax.jit"
        when the module did ``import jax as j``; ``jit`` -> "jax.jit"
        after ``from jax import jit``."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if head in self.name_aliases:
            base = self.name_aliases[head]
            return f"{base}.{rest}" if rest else base
        if head in self.module_aliases:
            base = self.module_aliases[head]
            return f"{base}.{rest}" if rest else base
        return name

    def is_jit_call(self, node: ast.AST) -> bool:
        """Call node whose callee is jax.jit / jax.pmap (or an alias)."""
        if not isinstance(node, ast.Call):
            return False
        return self.resolve(node.func) in ("jax.jit", "jax.pmap")


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing ``Class.method`` qualname
    and the stack of held locks (``with recv.some_lock:`` items)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.class_stack: List[str] = []
        self.func_stack: List[str] = []
        # each entry: ("recv.attr") for every lock held at this point
        self.lock_stack: List[str] = []

    @property
    def qualname(self) -> str:
        parts = self.class_stack + self.func_stack
        return ".".join(parts) if parts else "<module>"

    # ---- scope tracking ----
    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # ---- lock tracking ----
    def _visit_with(self, node):
        acquired = []
        for item in node.items:
            lock = lock_expr(item.context_expr)
            if lock is not None:
                acquired.append(lock)
        self.lock_stack.extend(acquired)
        self.generic_visit(node)
        del self.lock_stack[len(self.lock_stack) - len(acquired):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with


def lock_expr(expr: ast.AST) -> Optional[str]:
    """"recv.attr" when a with-item context expression acquires a lock:
    a bare attribute whose name smells like a mutex (``self._lock``,
    ``entry.deploy_lock``, ``self._cond``).  Calls (``ac.admit()``) and
    semaphores are not locks."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        attr = expr.attr.lower()
        if any(h in attr for h in _LOCK_NAME_HINTS):
            return f"{expr.value.id}.{expr.attr}"
    return None


def is_lock_ctor(ctx: ModuleContext, node: ast.AST) -> bool:
    """``threading.Lock()`` / ``RLock()`` / ``Condition()``."""
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func)
    if resolved is None:
        return False
    parts = resolved.split(".")
    return parts[-1] in _LOCK_CTORS and (
        len(parts) == 1 or parts[0] == "threading")


def iter_function_defs(ctx: "ModuleContext"):
    """Every (qualname, funcdef) in the module, nested defs included —
    the iteration order the CFG-based rules analyze functions in."""
    out: List[Tuple[str, ast.AST]] = []

    class V(QualnameVisitor):
        def _visit_func(self, node):
            self.func_stack.append(node.name)
            out.append((self.qualname, node))
            self.generic_visit(node)
            self.func_stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

    V(ctx).visit(ctx.tree)
    return out


def header_parts(st: ast.stmt) -> List[ast.AST]:
    """The sub-expressions a CFG node actually EVALUATES — compound
    statements' bodies are separate CFG nodes, so a rule scanning a
    node must look only at its header (an ``if``'s test, a ``for``'s
    iterator, a ``with``'s context expressions), never the body."""
    if isinstance(st, (ast.If, ast.While)):
        return [st.test]
    if isinstance(st, (ast.For, ast.AsyncFor)):
        return [st.target, st.iter]
    if isinstance(st, (ast.With, ast.AsyncWith)):
        parts: List[ast.AST] = []
        for item in st.items:
            parts.append(item.context_expr)
            if item.optional_vars is not None:
                parts.append(item.optional_vars)
        return parts
    if isinstance(st, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return []
    return [st]


def binding_targets(st: ast.stmt) -> List[ast.AST]:
    """Every individual binding target a statement rebinds — Assign
    (tuple/list/starred targets flattened, nested included), AnnAssign,
    ``for`` targets, ``del`` — shared by the v2 rules so "what does
    this statement rebind?" has exactly one answer.  AugAssign is
    deliberately NOT included: ``x += 1`` reads-modifies-writes, and
    the resource rules treat it as its own gen/kill event."""
    roots: List[ast.AST] = []
    if isinstance(st, ast.Assign):
        roots.extend(st.targets)
    elif isinstance(st, ast.AnnAssign):
        roots.append(st.target)
    elif isinstance(st, (ast.For, ast.AsyncFor)):
        roots.append(st.target)
    elif isinstance(st, ast.Delete):
        roots.extend(st.targets)
    out: List[ast.AST] = []
    while roots:
        t = roots.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            roots.extend(t.elts)
        elif isinstance(t, ast.Starred):
            roots.append(t.value)
        else:
            out.append(t)
    return out


def is_static_expr(node: ast.AST) -> bool:
    """True when an expression is host-static even if its leaves are
    traced: ``x.shape``, ``x.ndim == 2``, ``len(x)``,
    ``isinstance(x, T)``."""
    if isinstance(node, ast.Attribute):
        return node.attr in STATIC_ATTRS
    if isinstance(node, ast.Call):
        fn = last_name(node.func)
        return fn in STATIC_CALLS
    return False


def tainted_names(node: ast.AST, tainted: Set[str]) -> Set[str]:
    """Names from ``tainted`` that appear in ``node`` OUTSIDE
    static sub-expressions (shape/dtype reads, len() calls...)."""
    found: Set[str] = set()
    stack = [node]
    while stack:
        n = stack.pop()
        if is_static_expr(n):
            # descend only into the non-static parts (call args of
            # len() etc. stay static; attribute bases stay static)
            continue
        if isinstance(n, ast.Name) and n.id in tainted:
            found.add(n.id)
        stack.extend(ast.iter_child_nodes(n))
    return found


def parse_static_spec(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """Literal static_argnums / static_argnames of a jax.jit call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for c in _int_literals(kw.value):
                nums.add(c)
        elif kw.arg == "static_argnames":
            for s in _str_literals(kw.value):
                names.add(s)
    return nums, names


def _int_literals(node: ast.AST) -> Iterator[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _int_literals(e)


def _str_literals(node: ast.AST) -> Iterator[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _str_literals(e)
