"""zoolint command line.

    python -m analytics_zoo_tpu.tools.zoolint PATH... [--baseline FILE]
    python -m analytics_zoo_tpu.tools.zoolint --explain ZL701

Exit-code contract (test-pinned in tests/test_zoolint.py):

    0  clean (modulo baseline), or --explain of a known code
    2  usage — bad arguments, unknown --explain code, a broken
       baseline file (bad JSON / empty justification)
    3  findings — new findings not covered by the baseline

``--format json`` emits a machine-readable payload (findings,
suppressed, stale suppressions, a per-code summary) for CI —
``scripts/lint.sh`` consumes it to print its per-code summary line.
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
from typing import List, Optional

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       render_baseline)
from .catalog import explain
from .engine import lint_paths
from .hotpath import DEFAULT_HOT_ENTRIES

EXIT_CLEAN, EXIT_USAGE, EXIT_FINDINGS = 0, 2, 3


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX-aware static analyzer for the serving/training "
                    "stack (rule catalog: docs/dev/zoolint.md)")
    ap.add_argument("paths", nargs="*", help="files or trees to lint")
    ap.add_argument("--explain", metavar="ZLxxx", default=None,
                    help="print one rule's rationale, a minimal "
                         "bad/good example, and its docs anchor, "
                         "then exit (0 known / 2 unknown)")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as a baseline "
                         "skeleton (empty justifications) and exit 0")
    ap.add_argument("--root", default=None,
                    help="root for relative finding paths (default: cwd)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--hot-entries", default=",".join(DEFAULT_HOT_ENTRIES),
                    help="comma-separated final names treated as serving "
                         "hot-path entry points (ZL301/ZL302)")
    args = ap.parse_args(argv)

    if args.explain is not None:
        text = explain(args.explain.upper())
        if text is None:
            print(f"zoolint: unknown rule code {args.explain!r} "
                  "(see docs/dev/zoolint.md for the catalog)",
                  file=sys.stderr)
            return EXIT_USAGE
        print(text)
        return EXIT_CLEAN

    if not args.paths:
        ap.print_usage(sys.stderr)
        print("zoolint: error: paths required (or --explain ZLxxx)",
              file=sys.stderr)
        return EXIT_USAGE

    entries = tuple(e for e in args.hot_entries.split(",") if e)
    findings = lint_paths(args.paths, root=args.root, hot_entries=entries)

    if args.update_baseline:
        target = args.baseline or "zoolint_baseline.json"
        with open(target, "w", encoding="utf-8") as f:
            f.write(render_baseline(findings))
        print(f"zoolint: wrote {len(findings)} finding(s) to {target} — "
              "fill in every justification before committing")
        return EXIT_CLEAN

    suppressed, stale = [], []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except BaselineError as e:
            print(f"zoolint: {e}", file=sys.stderr)
            return EXIT_USAGE
        findings, suppressed, stale = apply_baseline(findings, baseline)

    rc = EXIT_FINDINGS if findings else EXIT_CLEAN
    if args.format == "json":
        by_code = collections.Counter(f.code for f in findings)
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": [vars(f) for f in suppressed],
            "stale_suppressions": stale,
            "summary": {"total": len(findings),
                        "by_code": dict(sorted(by_code.items())),
                        "suppressed": len(suppressed),
                        "stale": len(stale)},
            "exit": rc}, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"zoolint: stale suppression (matches nothing): "
                  f"{e['code']} {e['path']} {e['symbol']}",
                  file=sys.stderr)
        summary = (f"zoolint: {len(findings)} new finding(s), "
                   f"{len(suppressed)} baselined, {len(stale)} stale")
        print(summary, file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
