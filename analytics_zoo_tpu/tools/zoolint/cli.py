"""zoolint command line.

    python -m analytics_zoo_tpu.tools.zoolint PATH... [--baseline FILE]

Exit codes: 0 clean (modulo baseline), 2 new findings, 3 the baseline
file itself is broken (bad JSON / empty justification).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       render_baseline)
from .engine import lint_paths
from .hotpath import DEFAULT_HOT_ENTRIES


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX-aware static analyzer for the serving/training "
                    "stack (rule catalog: docs/dev/zoolint.md)")
    ap.add_argument("paths", nargs="+", help="files or trees to lint")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as a baseline "
                         "skeleton (empty justifications) and exit 0")
    ap.add_argument("--root", default=None,
                    help="root for relative finding paths (default: cwd)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--hot-entries", default=",".join(DEFAULT_HOT_ENTRIES),
                    help="comma-separated final names treated as serving "
                         "hot-path entry points (ZL301/ZL302)")
    args = ap.parse_args(argv)

    entries = tuple(e for e in args.hot_entries.split(",") if e)
    findings = lint_paths(args.paths, root=args.root, hot_entries=entries)

    if args.update_baseline:
        target = args.baseline or "zoolint_baseline.json"
        with open(target, "w", encoding="utf-8") as f:
            f.write(render_baseline(findings))
        print(f"zoolint: wrote {len(findings)} finding(s) to {target} — "
              "fill in every justification before committing")
        return 0

    suppressed, stale = [], []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except BaselineError as e:
            print(f"zoolint: {e}", file=sys.stderr)
            return 3
        findings, suppressed, stale = apply_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "suppressed": [vars(f) for f in suppressed],
            "stale_suppressions": stale}, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"zoolint: stale suppression (matches nothing): "
                  f"{e['code']} {e['path']} {e['symbol']}",
                  file=sys.stderr)
        summary = (f"zoolint: {len(findings)} new finding(s), "
                   f"{len(suppressed)} baselined, {len(stale)} stale")
        print(summary, file=sys.stderr)
    return 2 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
