"""zoolint command line.

    python -m analytics_zoo_tpu.tools.zoolint PATH... [--baseline FILE]
    python -m analytics_zoo_tpu.tools.zoolint --explain ZL701
    python -m analytics_zoo_tpu.tools.zoolint contracts --check

Exit-code contract (test-pinned in tests/test_zoolint.py):

    0  clean (modulo baseline), or --explain of a known code, or a
       contracts snapshot that matches the committed one
    2  usage — bad arguments, unknown --explain code, a broken
       baseline file (bad JSON / empty justification), a missing
       snapshot under ``contracts --check``
    3  findings — new findings not covered by the baseline, or
       contract drift against the committed snapshot

``--format json`` emits a machine-readable payload (findings,
suppressed, stale suppressions, a per-code summary) for CI —
``scripts/lint.sh`` consumes it to print its per-code summary line.

``--changed-only`` scopes the REPORTED findings to files touched per
git (``git diff --name-only HEAD`` + untracked): the lint still runs
over everything (cross-module rules need the whole package), only the
verdict is scoped — the pre-commit loop for a package whose full
baseline someone else owns.

``contracts`` is the committed-contract workflow: it renders the
ContractIndex (wire ops, error codes, env vars, metric families) as
deterministic JSON.  ``--update`` writes ``contracts_snapshot.json``;
``--check`` diffs the live index against the committed file and exits
3 on drift, so a protocol change NOT reflected in the snapshot (and
therefore never seen in review) fails CI.
"""

from __future__ import annotations

import argparse
import collections
import json
import subprocess
import sys
from typing import List, Optional, Set

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       render_baseline)
from .catalog import explain
from .engine import lint_paths
from .hotpath import DEFAULT_HOT_ENTRIES

EXIT_CLEAN, EXIT_USAGE, EXIT_FINDINGS = 0, 2, 3


def _changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths touched per git (tracked diffs vs HEAD +
    untracked), None when git is unavailable (degrade to full scope —
    never silently report clean because git broke)."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        out.update(l.strip() for l in res.stdout.splitlines()
                   if l.strip())
    return out


def _contracts_main(argv: List[str]) -> int:
    import os

    from .engine import _iter_py_files
    from .context import ModuleContext
    from .rules_contracts import ContractIndex

    ap = argparse.ArgumentParser(
        prog="zoolint contracts",
        description="render / check the committed distributed-contract "
                    "snapshot (ops, errors, env vars, metric families)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or trees to index "
                         "(default: analytics_zoo_tpu under --root)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: cwd); locates the "
                         "default paths and the snapshot file")
    ap.add_argument("--snapshot", default=None,
                    help="snapshot path (default: "
                         "contracts_snapshot.json under --root)")
    g = ap.add_mutually_exclusive_group()
    g.add_argument("--check", action="store_true",
                   help="diff the live index against the committed "
                        "snapshot: exit 0 match / 3 drift / 2 missing")
    g.add_argument("--update", action="store_true",
                   help="write the committed snapshot from the live "
                        "index")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or [os.path.join(root, "analytics_zoo_tpu")]
    snap_path = args.snapshot or os.path.join(
        root, "contracts_snapshot.json")

    ctxs = []
    for fp in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fp), root).replace(
            os.sep, "/")
        try:
            with open(fp, "r", encoding="utf-8") as f:
                ctxs.append(ModuleContext(rel, f.read()))
        except (SyntaxError, UnicodeDecodeError):
            continue  # the lint proper reports ZL000 for these
    live = ContractIndex(ctxs).snapshot()
    rendered = json.dumps(live, indent=2, sort_keys=True) + "\n"

    if args.update:
        with open(snap_path, "w", encoding="utf-8") as f:
            f.write(rendered)
        print(f"zoolint contracts: wrote {snap_path}")
        return EXIT_CLEAN
    if args.check:
        try:
            with open(snap_path, "r", encoding="utf-8") as f:
                committed = json.load(f)
        except OSError:
            print(f"zoolint contracts: no committed snapshot at "
                  f"{snap_path} — run `zoolint contracts --update` "
                  "and commit it", file=sys.stderr)
            return EXIT_USAGE
        except ValueError as e:
            print(f"zoolint contracts: {snap_path} is not valid "
                  f"JSON: {e}", file=sys.stderr)
            return EXIT_USAGE
        if committed == live:
            print("zoolint contracts: snapshot matches")
            return EXIT_CLEAN
        for section in sorted(set(live) | set(committed)):
            if live.get(section) != committed.get(section):
                print(f"zoolint contracts: drift in {section!r}:\n"
                      f"  committed: "
                      f"{json.dumps(committed.get(section), sort_keys=True)}\n"
                      f"  live:      "
                      f"{json.dumps(live.get(section), sort_keys=True)}",
                      file=sys.stderr)
        print("zoolint contracts: drift — review the change, then "
              "`zoolint contracts --update` and commit the snapshot",
              file=sys.stderr)
        return EXIT_FINDINGS
    print(rendered, end="")
    return EXIT_CLEAN


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "contracts":
        return _contracts_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX-aware static analyzer for the serving/training "
                    "stack (rule catalog: docs/dev/zoolint.md)")
    ap.add_argument("paths", nargs="*", help="files or trees to lint")
    ap.add_argument("--explain", metavar="ZLxxx", default=None,
                    help="print one rule's rationale, a minimal "
                         "bad/good example, and its docs anchor, "
                         "then exit (0 known / 2 unknown)")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as a baseline "
                         "skeleton (empty justifications) and exit 0")
    ap.add_argument("--root", default=None,
                    help="root for relative finding paths (default: cwd)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files git considers "
                         "changed (diff vs HEAD + untracked); the "
                         "analysis itself still covers every path")
    ap.add_argument("--hot-entries", default=",".join(DEFAULT_HOT_ENTRIES),
                    help="comma-separated final names treated as serving "
                         "hot-path entry points (ZL301/ZL302)")
    args = ap.parse_args(argv)

    if args.explain is not None:
        text = explain(args.explain.upper())
        if text is None:
            print(f"zoolint: unknown rule code {args.explain!r} "
                  "(see docs/dev/zoolint.md for the catalog)",
                  file=sys.stderr)
            return EXIT_USAGE
        print(text)
        return EXIT_CLEAN

    if not args.paths:
        ap.print_usage(sys.stderr)
        print("zoolint: error: paths required (or --explain ZLxxx)",
              file=sys.stderr)
        return EXIT_USAGE

    entries = tuple(e for e in args.hot_entries.split(",") if e)
    findings = lint_paths(args.paths, root=args.root, hot_entries=entries)

    if args.update_baseline:
        target = args.baseline or "zoolint_baseline.json"
        with open(target, "w", encoding="utf-8") as f:
            f.write(render_baseline(findings))
        print(f"zoolint: wrote {len(findings)} finding(s) to {target} — "
              "fill in every justification before committing")
        return EXIT_CLEAN

    suppressed, stale = [], []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except BaselineError as e:
            print(f"zoolint: {e}", file=sys.stderr)
            return EXIT_USAGE
        findings, suppressed, stale = apply_baseline(findings, baseline)

    if args.changed_only:
        import os
        changed = _changed_files(os.path.abspath(args.root
                                                 or os.getcwd()))
        if changed is not None:
            findings = [f for f in findings if f.path in changed]
        else:
            print("zoolint: --changed-only: git unavailable, "
                  "reporting full scope", file=sys.stderr)

    rc = EXIT_FINDINGS if findings else EXIT_CLEAN
    if args.format == "json":
        by_code = collections.Counter(f.code for f in findings)
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": [vars(f) for f in suppressed],
            "stale_suppressions": stale,
            "summary": {"total": len(findings),
                        "by_code": dict(sorted(by_code.items())),
                        "suppressed": len(suppressed),
                        "stale": len(stale)},
            "exit": rc}, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"zoolint: stale suppression (matches nothing): "
                  f"{e['code']} {e['path']} {e['symbol']}",
                  file=sys.stderr)
        summary = (f"zoolint: {len(findings)} new finding(s), "
                   f"{len(suppressed)} baselined, {len(stale)} stale")
        print(summary, file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
