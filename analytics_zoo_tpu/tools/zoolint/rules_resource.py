"""Resource-balance rules over the exception-path CFG (ZL701/ZL702).

The dominant residual bug class after PRs 5-8 is a *protocol* bug: a
resource taken on the way in — a semaphore slot, an in-flight counter,
a queue seat — that every exit path must give back, and the exception
exits don't.  The normal path gets reviewed; the unwind leaks.  Both
rules run a forward may-analysis ("which resources may still be held
here?") over :mod:`cfg` and flag anything still held when control
reaches the function's exceptional exit (``RAISE``).

ZL701 — acquire/release call pairing.  ``recv.acquire()`` as a bare
  statement marks ``recv`` held; ``recv.release()`` (same dotted
  receiver, or the same final attribute through a helper whose body
  releases it) frees it.  Held at an exceptional exit → finding.
  Deliberately NOT a gen event: conditional acquires (``blocking=False``
  / ``timeout=`` / the result assigned and branched on — the crash-net
  ``got = lock.acquire(timeout=1.0)`` idiom) and ``with lock:`` (balanced
  by construction).  Normal-path exits holding the resource are also
  deliberately allowed: returning while holding is how ownership
  transfer works (``_acquire_slot`` hands its slot to the dispatch),
  and the caller can see it; an exception unwinding through the caller
  cannot.

ZL702 — counter balance.  A *tracked counter* is an attribute the
  module both ``+=``s and ``-=``s somewhere (``_waiting``, ``_running``,
  ``slot_inflight[i]``, ...) — one-way stats counters never track.  An
  increment marks the counter held; a decrement of the same attribute,
  an outright re-assignment, or a call to a same-module function whose
  body decrements it (``self._grant_locked()`` hands the seat on)
  frees it.  Held at an exceptional exit → finding: the in-flight
  count stays up forever, shrinking effective capacity one exception
  at a time — exactly the PR 6 ``_acquire`` KeyboardInterrupt seat
  leak, and the hedge-loser slot accounting before it.
"""

from __future__ import annotations

import ast
import collections
from typing import Dict, List, Optional, Set, Tuple

from .cfg import CFG, build_cfg
from .context import (ModuleContext, binding_targets, dotted_name,
                      header_parts, iter_function_defs, last_name,
                      walk_shallow)
from .dataflow import solve_forward
from .findings import Finding

_RES, _CNT = "res", "cnt"


def _counter_attr(target: ast.AST) -> Optional[str]:
    """The attribute name of an ``x.attr`` / ``x.attr[i]`` aug-assign
    target (the counter identity — receivers vary, the attr is the
    protocol)."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _tracked_counters(ctx: ModuleContext) -> Set[str]:
    """Attrs with BOTH an increment and a decrement in this module,
    where at least one increment is by literal ``1`` — the discrete-
    seat signature.  Fractional error accumulators (the canary
    router's ``_canary_acc += fraction`` / ``-= 1.0`` pair) share the
    +=/-= shape but deliberately KEEP their balance across error
    exits, so amount-shaped updates never track."""
    incs: Set[str] = set()
    unit_incs: Set[str] = set()
    decs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.AugAssign):
            attr = _counter_attr(node.target)
            if attr is None:
                continue
            if isinstance(node.op, ast.Add):
                incs.add(attr)
                if (isinstance(node.value, ast.Constant)
                        and node.value.value == 1):
                    unit_incs.add(attr)
            elif isinstance(node.op, ast.Sub):
                decs.add(attr)
    return incs & unit_incs & decs


def _releasing_helpers(ctx: ModuleContext
                       ) -> Tuple[Dict[str, Set[str]],
                                  Dict[str, Set[str]]]:
    """Name-based one-hop call graph for kills: final function name ->
    {counter attrs it decrements} and -> {receiver tails it
    .release()s}.  A call to such a helper hands the resource on —
    ``self._grant_locked()`` decrements ``_waiting`` for the granted
    ticket, so the seat is no longer this function's to leak."""
    decrements: Dict[str, Set[str]] = collections.defaultdict(set)
    releases: Dict[str, Set[str]] = collections.defaultdict(set)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.AugAssign) \
                    and isinstance(sub.op, ast.Sub):
                attr = _counter_attr(sub.target)
                if attr is not None:
                    decrements[node.name].add(attr)
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Attribute)
                  and sub.func.attr == "release"):
                tail = last_name(sub.func.value)
                if tail is not None:
                    releases[node.name].add(tail)
    return decrements, releases


def _unconditional_acquire(st: ast.stmt) -> Optional[str]:
    """The dotted receiver of a bare ``recv.acquire()`` statement, None
    for conditional forms (module docstring)."""
    if not (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)):
        return None
    call = st.value
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"):
        return None
    if call.args or any(kw.arg in ("blocking", "timeout")
                        for kw in call.keywords):
        return None
    return dotted_name(call.func.value)


def rule_resource_balance(ctx: ModuleContext) -> List[Finding]:
    tracked = _tracked_counters(ctx)
    decrements, releases = _releasing_helpers(ctx)
    findings: List[Finding] = []

    for qual, fd in iter_function_defs(ctx):
        cfg = build_cfg(fd)
        if not cfg.preds.get(CFG.RAISE):
            continue  # no exceptional exit — nothing to leak through

        def transfer(node: int, state, _cfg=cfg):
            st = _cfg.stmts.get(node)
            if st is None:
                return state
            gens: Set[Tuple] = set()
            kill_cnt: Set[str] = set()
            kill_res: Set[str] = set()
            recv = _unconditional_acquire(st)
            if recv is not None:
                gens.add((_RES, recv, st.lineno))
            for part in header_parts(st):
                for n in walk_shallow([part]):
                    if isinstance(n, ast.AugAssign):
                        attr = _counter_attr(n.target)
                        if attr is None or attr not in tracked:
                            continue
                        if isinstance(n.op, ast.Add):
                            gens.add((_CNT, attr, n.lineno))
                        elif isinstance(n.op, ast.Sub):
                            kill_cnt.add(attr)
                    elif isinstance(n, ast.Call):
                        name = last_name(n.func)
                        if (isinstance(n.func, ast.Attribute)
                                and n.func.attr == "release"):
                            d = dotted_name(n.func.value)
                            if d is not None:
                                kill_res.add(d)
                                kill_res.add(d.rsplit(".", 1)[-1])
                        if name in decrements:
                            kill_cnt |= decrements[name]
                        if name in releases:
                            kill_res |= releases[name]
            for t in binding_targets(st):
                attr = _counter_attr(t)
                if attr is not None:
                    kill_cnt.add(attr)
            out = set()
            for el in state:
                kind, key, _line = el
                if kind == _CNT and key in kill_cnt:
                    continue
                if kind == _RES and (
                        key in kill_res
                        or key.rsplit(".", 1)[-1] in kill_res):
                    continue
                out.add(el)
            return frozenset(out | gens)

        sol = solve_forward(cfg, transfer)
        for kind, key, line in sorted(sol.in_state(CFG.RAISE),
                                      key=lambda e: (e[2], e[1])):
            if kind == _RES:
                findings.append(Finding(
                    "ZL701", ctx.path, line, 0, qual,
                    f"{key}.acquire() here is not released on an "
                    "exception path out of this function: the caller "
                    "unwinds still owning the slot and nothing ever "
                    "returns it — release in a finally/except-"
                    "BaseException unwind before re-raising"))
            else:
                findings.append(Finding(
                    "ZL702", ctx.path, line, 0, qual,
                    f"counter .{key} incremented here is not "
                    "decremented on an exception path out of this "
                    "function: the in-flight count leaks on unwind "
                    "and capacity shrinks one exception at a time — "
                    "balance it in the except-BaseException unwind "
                    "(PR 6 _acquire seat-leak pattern)"))
    return findings
