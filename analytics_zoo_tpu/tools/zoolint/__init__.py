"""zoolint: JAX-aware static analyzer + runtime sanitizer.

Static half (stdlib-only, no jax import):

    from analytics_zoo_tpu.tools.zoolint import lint_paths
    findings = lint_paths(["analytics_zoo_tpu"])

Rule codes (catalog with rationale: docs/dev/zoolint.md):

    ZL101/ZL102/ZL103  recompile hazards (jit-in-loop, jit-per-call,
                       unhashable static argument)
    ZL201/ZL202/ZL203  tracer leaks (host cast / Python branch / host
                       materialization inside jit)
    ZL301/ZL302        host sync on the serving hot path
    ZL401/ZL402        lock discipline (mixed-lock writes, blocking
                       device work under a lock)
    ZL501/ZL502        thread lifecycle (unjoined non-daemon threads,
                       unbounded queues)
    ZL601              bare print/stdlib logging on the hot path (use
                       the structured logger with request-id fields)

Runtime half (imports jax lazily, on first use):

    with zoolint.sanitize(max_compiles=0):
        hot_loop()
"""

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       render_baseline)
from .engine import ALL_CODES, lint_paths
from .findings import Finding
from .hotpath import DEFAULT_HOT_ENTRIES

__all__ = ["ALL_CODES", "BaselineError", "DEFAULT_HOT_ENTRIES",
           "Finding", "RecompileDetected", "SanitizeError",
           "SanitizeReport", "apply_baseline", "lint_paths",
           "load_baseline", "render_baseline", "sanitize"]


def __getattr__(name):
    # sanitize + its error types live behind a lazy import so linting
    # never drags jax into the process
    if name in ("sanitize", "SanitizeError", "RecompileDetected",
                "SanitizeReport"):
        import importlib
        mod = importlib.import_module(".sanitizer", __name__)
        return getattr(mod, name)
    raise AttributeError(name)
