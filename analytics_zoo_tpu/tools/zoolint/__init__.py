"""zoolint: JAX-aware static analyzer + runtime sanitizer.

Static half (stdlib-only, no jax import):

    from analytics_zoo_tpu.tools.zoolint import lint_paths
    findings = lint_paths(["analytics_zoo_tpu"])

Rule codes (catalog with rationale: docs/dev/zoolint.md):

    ZL101/ZL102/ZL103  recompile hazards (jit-in-loop, jit-per-call,
                       unhashable static argument)
    ZL201/ZL202/ZL203  tracer leaks (host cast / Python branch / host
                       materialization inside jit)
    ZL301/ZL302        host sync on the serving hot path
    ZL401/ZL402        lock discipline (mixed-lock writes, blocking
                       device work under a lock)
    ZL501/ZL502        thread lifecycle (unjoined non-daemon threads,
                       unbounded queues)
    ZL601              bare print/stdlib logging on the hot path (use
                       the structured logger with request-id fields)
    ZL701/ZL702        resource balance over the exception-path CFG
                       (acquire/release pairing, in-flight counter
                       increments leaked on unwind)
    ZL711              use-after-donate (reading a buffer after it was
                       passed at a donate_argnums position)
    ZL721              check-then-deref of a shared mutable attribute
                       (re-read instead of a local snapshot)
    ZL731              lock-order cycles in the global lexical
                       lock-acquisition graph
    ZL801              wire ops sent without a handler (or handled
                       without a sender); encode/decode key asymmetry
    ZL802              ServingError subclasses that cannot round-trip
                       the wire error envelope
    ZL811              metric family schema conflicts and docs drift
    ZL812              ZOO_* env reads outside the envcontract module
    ZL821              compile-path config reads missing from the
                       executable-store fingerprint

v3 rules (ZL8xx) are cross-module: one :class:`ContractIndex` built
over every file at once checks the agreements BETWEEN modules (wire
ops, error envelopes, metric schemas, env knobs, fingerprint keys).
``zoolint contracts`` renders the same index as a committed snapshot
(``contracts_snapshot.json``) that CI diffs on every run.

v2 rules run real dataflow: :mod:`cfg` builds a per-function CFG with
explicit exception edges, :mod:`dataflow` iterates forward
may-analyses over it.  ``--explain ZLxxx`` prints any rule's
rationale + minimal bad/good pair.

Runtime half (imports jax lazily, on first use):

    with zoolint.sanitize(max_compiles=0):
        hot_loop()

    # invariant-snapshot mode: gauge counters + live thread count must
    # come back level across a quiesced serve window
    with zoolint.sanitize(max_compiles=0,
                          invariants=lambda: {"pending": ac.pending}):
        warmed_serve_window()
"""

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       render_baseline)
from .catalog import CATALOG, explain
from .cfg import CFG, build_cfg
from .dataflow import solve_forward
from .engine import ALL_CODES, lint_paths
from .findings import Finding
from .hotpath import DEFAULT_HOT_ENTRIES
from .rules_contracts import ContractIndex, rule_contracts

__all__ = ["ALL_CODES", "BaselineError", "CATALOG", "CFG",
           "ContractIndex", "DEFAULT_HOT_ENTRIES", "Finding",
           "InvariantLeakDetected", "RecompileDetected",
           "SanitizeError", "SanitizeReport", "apply_baseline",
           "build_cfg", "explain", "lint_paths", "load_baseline",
           "render_baseline", "rule_contracts", "sanitize",
           "solve_forward"]


def __getattr__(name):
    # sanitize + its error types live behind a lazy import so linting
    # never drags jax into the process
    if name in ("sanitize", "SanitizeError", "RecompileDetected",
                "InvariantLeakDetected", "SanitizeReport"):
        import importlib
        mod = importlib.import_module(".sanitizer", __name__)
        return getattr(mod, name)
    raise AttributeError(name)
