"""JAX-specific static rules.

Recompile hazards
  ZL101  jax.jit / jax.pmap invoked inside a loop body — a fresh wrapper
         (with a fresh trace cache) per iteration.
  ZL102  immediately-invoked jit: ``jax.jit(f)(x)`` builds a new wrapper
         per call, so every call re-traces.
  ZL103  unhashable value (list/dict/set display) passed in a position
         the jit declared static — TypeError at best, a compile per
         call-site mutation at worst.

Tracer leaks (inside jit-decorated scopes)
  ZL201  float()/int()/bool() on a possibly-traced value.
  ZL202  Python ``if``/``while`` branching on a possibly-traced value
         (static .shape/.ndim/len() tests are exempt).
  ZL203  host materialization of a possibly-traced value:
         np.asarray/np.array, ``.item()``, ``.tolist()``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .context import (ModuleContext, QualnameVisitor, last_name,
                      parse_static_spec, tainted_names, walk_shallow)
from .findings import Finding

_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)


# --------------------------------------------------------- ZL101 / ZL102
class _RecompileVisitor(QualnameVisitor):
    def __init__(self, ctx: ModuleContext):
        super().__init__(ctx)
        self.findings: List[Finding] = []
        self._reported: set = set()

    def _visit_loop(self, node):
        # flag jit calls lexically in the loop body — including nested
        # defs' decorators (they run per iteration) but not nested defs'
        # bodies (those run when called)
        for child in walk_shallow(node.body + node.orelse):
            if self.ctx.is_jit_call(child) and \
                    id(child) not in self._reported:
                self._reported.add(id(child))  # nested loops: report once
                self.findings.append(Finding(
                    "ZL101", self.ctx.path, child.lineno, child.col_offset,
                    self.qualname,
                    "jax.jit/pmap invoked inside a loop: each iteration "
                    "builds a fresh wrapper with an empty trace cache — "
                    "hoist the jit out and reuse it"))
        self.generic_visit(node)

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call):
        if self.ctx.is_jit_call(node.func):
            self.findings.append(Finding(
                "ZL102", self.ctx.path, node.lineno, node.col_offset,
                self.qualname,
                "immediately-invoked jit `jax.jit(f)(x)`: a new wrapper "
                "per call means a re-trace per call — bind `g = "
                "jax.jit(f)` once and call g"))
        self.generic_visit(node)


def rule_recompile(ctx: ModuleContext) -> List[Finding]:
    v = _RecompileVisitor(ctx)
    v.visit(ctx.tree)
    # ZL101 sites also match ZL102's pattern only when immediately
    # invoked; the visitor reports each pattern independently.
    return v.findings


# ----------------------------------------------------------------- ZL103
def rule_unhashable_static(ctx: ModuleContext) -> List[Finding]:
    """Track ``g = jax.jit(f, static_argnums=...)`` bindings, then flag
    calls of ``g`` passing an unhashable display in a static position."""
    findings: List[Finding] = []
    static_of: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and ctx.is_jit_call(node.value)):
            nums, names = parse_static_spec(node.value)
            if nums or names:
                static_of[node.targets[0].id] = (nums, names)

    class V(QualnameVisitor):
        def visit_Call(self, node: ast.Call):
            name = node.func.id if isinstance(node.func, ast.Name) else None
            spec = static_of.get(name)
            if spec is not None:
                nums, names = spec
                for i, arg in enumerate(node.args):
                    if i in nums and isinstance(arg, _UNHASHABLE):
                        findings.append(Finding(
                            "ZL103", ctx.path, arg.lineno, arg.col_offset,
                            self.qualname,
                            f"unhashable literal passed to {name}() in "
                            f"static position {i}: static jit arguments "
                            "must be hashable (use a tuple)"))
                for kw in node.keywords:
                    if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                        findings.append(Finding(
                            "ZL103", ctx.path, kw.value.lineno,
                            kw.value.col_offset, self.qualname,
                            f"unhashable literal passed to {name}() for "
                            f"static argument {kw.arg!r} (use a tuple)"))
            self.generic_visit(node)

    V(ctx).visit(ctx.tree)
    return findings


# --------------------------------------------------- ZL201/ZL202/ZL203
def _jitted_functions(ctx: ModuleContext):
    """(funcdef, static_names) for every function jitted in this module:
    decorated with @jax.jit / @partial(jax.jit, ...), or a named def
    passed to jax.jit() somewhere in the module."""
    jitted: Dict[ast.AST, Set[str]] = {}
    by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                spec = _jit_decorator_spec(ctx, dec)
                if spec is not None:
                    jitted[node] = _static_names_of(node, *spec)
    for node in ast.walk(ctx.tree):
        if ctx.is_jit_call(node):
            target = node.args[0] if node.args else None
            if isinstance(target, ast.Name) and target.id in by_name:
                fd = by_name[target.id]
                if fd not in jitted:
                    nums, names = parse_static_spec(node)
                    jitted[fd] = _static_names_of(fd, nums, names)
    return jitted


def _jit_decorator_spec(ctx: ModuleContext, dec: ast.AST
                        ) -> Optional[Tuple[Set[int], Set[str]]]:
    if ctx.resolve(dec) in ("jax.jit", "jax.pmap"):
        return set(), set()
    if isinstance(dec, ast.Call):
        resolved = ctx.resolve(dec.func)
        if resolved in ("jax.jit", "jax.pmap"):
            return parse_static_spec(dec)
        if resolved in ("functools.partial", "partial") and dec.args \
                and ctx.resolve(dec.args[0]) in ("jax.jit", "jax.pmap"):
            return parse_static_spec(dec)
    return None


def _static_names_of(fd, nums: Set[int], names: Set[str]) -> Set[str]:
    params = [a.arg for a in fd.args.posonlyargs + fd.args.args]
    static = set(names)
    for i in nums:
        if 0 <= i < len(params):
            static.add(params[i])
    return static


def rule_tracer_leaks(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    for fd, static in _jitted_functions(ctx).items():
        params = {a.arg for a in
                  fd.args.posonlyargs + fd.args.args + fd.args.kwonlyargs}
        tainted = params - static
        if not tainted:
            continue
        # one cheap forward taint pass: names assigned from tainted exprs
        for stmt in ast.walk(fd):
            if isinstance(stmt, ast.Assign) and \
                    tainted_names(stmt.value, tainted):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        qual = fd.name
        for node in ast.walk(fd):
            if isinstance(node, ast.Call):
                fn = last_name(node.func)
                if fn in _HOST_CASTS and isinstance(node.func, ast.Name) \
                        and node.args and \
                        tainted_names(node.args[0], tainted):
                    findings.append(Finding(
                        "ZL201", ctx.path, node.lineno, node.col_offset,
                        qual,
                        f"{fn}() on a traced value inside jit: raises "
                        "TracerConversionError (or silently constant-"
                        "folds) — use lax primitives or hoist out"))
                elif fn in _HOST_METHODS and \
                        isinstance(node.func, ast.Attribute) and \
                        tainted_names(node.func.value, tainted):
                    findings.append(Finding(
                        "ZL203", ctx.path, node.lineno, node.col_offset,
                        qual,
                        f".{fn}() materializes a traced value to host "
                        "inside jit"))
                elif ctx.resolve(node.func) in (
                        "numpy.asarray", "numpy.array") and node.args \
                        and tainted_names(node.args[0], tainted):
                    findings.append(Finding(
                        "ZL203", ctx.path, node.lineno, node.col_offset,
                        qual,
                        "np.asarray/np.array on a traced value inside "
                        "jit forces a host round-trip per trace — use "
                        "jnp instead"))
            elif isinstance(node, (ast.If, ast.While)):
                hits = tainted_names(node.test, tainted)
                if hits and not _is_identity_test(node.test):
                    findings.append(Finding(
                        "ZL202", ctx.path, node.lineno, node.col_offset,
                        qual,
                        f"Python branch on possibly-traced "
                        f"{sorted(hits)} inside jit: tracers have no "
                        "truth value — use lax.cond/jnp.where, or mark "
                        "the argument static"))
    return findings


def _is_identity_test(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` never touches __bool__."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))
