"""End-to-end observability for the serving stack.

Three halves, importable with zero jax cost (jax loads lazily inside
``profile.install()`` only):

* :mod:`.trace` — per-request :class:`Span`/:class:`Tracer` with
  explicit cross-thread handoff through the coalescer, a bounded ring
  buffer of recent traces, and per-phase aggregation;
* :mod:`.metrics` — the unified :class:`MetricsRegistry` (labeled
  counters/gauges, re-homed ``LatencyWindow``/``Counters``) with
  Prometheus text exposition and the stdlib round-trip parser;
* :mod:`.profile` — XLA hooks turning ``backend_compile`` events,
  explicit transfers, and live-buffer counts into metrics/span events.

Plus :mod:`.log` — the structured JSON logger with request-id
correlation (the ZL601-sanctioned replacement for ``print``/stdlib
``logging`` on hot paths).

See docs/observability.md for the span taxonomy and wiring examples.
"""

from . import profile, trace
from .log import StructuredLogger, get_logger
from .metrics import (Counters, Family, LatencyWindow, MetricsRegistry,
                      parse_prometheus_text, render_prometheus,
                      summary_family)
from .trace import PHASES, Span, Tracer, activate, current_span

__all__ = [
    "Counters", "Family", "LatencyWindow", "MetricsRegistry", "PHASES",
    "Span", "StructuredLogger", "Tracer", "activate", "current_span",
    "get_logger", "parse_prometheus_text", "profile",
    "render_prometheus", "summary_family", "trace",
]
