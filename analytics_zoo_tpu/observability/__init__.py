"""End-to-end observability for the serving stack.

Three halves, importable with zero jax cost (jax loads lazily inside
``profile.install()`` only):

* :mod:`.trace` — per-request :class:`Span`/:class:`Tracer` with
  explicit cross-thread handoff through the coalescer, a bounded ring
  buffer of recent traces, and per-phase aggregation;
* :mod:`.metrics` — the unified :class:`MetricsRegistry` (labeled
  counters/gauges, re-homed ``LatencyWindow``/``Counters``) with
  Prometheus text exposition and the stdlib round-trip parser;
* :mod:`.profile` — XLA hooks turning ``backend_compile`` events,
  explicit transfers, and live-buffer counts into metrics/span events.

Plus :mod:`.log` — the structured JSON logger with request-id
correlation (the ZL601-sanctioned replacement for ``print``/stdlib
``logging`` on hot paths), now auto-stamping ``rank``/``incarnation``
from the supervisor env contract.

Cross-process (this PR's layer): :mod:`.flightrec` — the crash-safe
per-process flight recorder the supervising launcher harvests into
``pod_postmortem.json`` after reaping a worker — and :mod:`.aggregate`
— per-rank Prometheus snapshots merged into one pod-level scrape
(``python -m analytics_zoo_tpu.observability.aggregate``).

See docs/observability.md for the span taxonomy and wiring examples.
"""

from . import aggregate, flightrec, profile, trace
from .flightrec import FlightRecorder
from .log import StructuredLogger, get_logger
from .metrics import (Counters, Family, LatencyWindow, MetricsRegistry,
                      parse_prometheus_text, process_info_family,
                      render_prometheus, summary_family)
from .trace import (PHASES, TRAIN_PHASES, Span, Tracer, activate,
                    current_span)

__all__ = [
    "Counters", "Family", "FlightRecorder", "LatencyWindow",
    "MetricsRegistry", "PHASES", "Span", "StructuredLogger",
    "TRAIN_PHASES", "Tracer", "activate", "aggregate", "current_span",
    "flightrec", "get_logger", "parse_prometheus_text",
    "process_info_family", "profile", "render_prometheus",
    "summary_family", "trace",
]
