"""Unified metrics: primitives, a labeled registry, and Prometheus
text-format exposition with a stdlib round-trip parser.

Three layers, smallest first:

* **primitives** — :class:`Counters` (a named bag of monotonic ints)
  and :class:`LatencyWindow` (sliding-window exact percentiles), both
  re-homed here from ``serving/metrics.py`` (which re-exports them for
  back-compat) so training, serving, and tools share one vocabulary;
* **:class:`MetricsRegistry`** — labeled counter/gauge families plus
  pluggable *collectors* (callables returning :class:`Family` lists at
  scrape time) for snapshot-oriented sources like the serving control
  plane, the tracer's phase aggregates, and the XLA profile hooks;
* **exposition** — ``render_prometheus()`` emits the Prometheus text
  format (``# HELP``/``# TYPE`` + escaped labels), and
  ``parse_prometheus_text()`` is the tiny stdlib parser the CI smoke
  gate round-trips the exposition through: every sample line must
  re-parse, so a malformed label escape can never ship silently.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from .. import envcontract


# --------------------------------------------------------- primitives
class LatencyWindow:
    """Sliding window of the most recent N request latencies with
    percentile snapshots.

    A bounded deque, not a histogram: serving windows are small enough
    (default 2048 samples) that exact percentiles over the raw samples
    are cheaper and more faithful than bucket interpolation, and the
    window self-ages — a traffic spike's tail latencies wash out after
    N fresh requests instead of polluting a cumulative histogram
    forever.

    Percentiles are nearest-rank over the sorted window: the index is
    ``round(p/100 * (n-1))`` clamped into the window, so a single
    sample answers every percentile with itself and p0/p100 are the
    window min/max exactly.
    """

    def __init__(self, maxlen: int = 2048):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._count = 0
        self._total_s = 0.0

    def add(self, seconds: float):
        with self._lock:
            self._samples.append(seconds)
            self._count += 1
            self._total_s += seconds

    @property
    def count(self) -> int:
        """Total samples ever added (not just the current window)."""
        with self._lock:
            return self._count

    def percentile(self, pct: float) -> Optional[float]:
        """Nearest-rank percentile over the current window, in SECONDS
        (None while empty).  The hedging threshold reads this directly
        — a full ``snapshot()`` per dispatched group would sort the
        window three times for two discarded quantiles."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        k = min(len(data) - 1,
                max(0, int(round((pct / 100.0) * (len(data) - 1)))))
        return data[k]

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            data = sorted(self._samples)
            count, total = self._count, self._total_s

        def pick(pct):
            if not data:
                return None
            k = min(len(data) - 1,
                    max(0, int(round((pct / 100.0) * (len(data) - 1)))))
            return round(data[k] * 1e3, 3)

        return {"count": count,
                "mean_ms": (round(total / count * 1e3, 3)
                            if count else None),
                "total_s": round(total, 6),
                "p50_ms": pick(50), "p90_ms": pick(90),
                "p99_ms": pick(99),
                "window": len(data)}


class Counters:
    """A named bag of monotonically-increasing integers."""

    def __init__(self, *names: str):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {n: 0 for n in names}

    def inc(self, name: str, by: int = 1):
        with self._lock:
            self._c[name] = self._c.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)


# ----------------------------------------------------------- registry
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class Family:
    """One exposition family: metric type + name + help + samples.

    ``samples`` is a list of ``(labels_dict, value)`` pairs; for
    summaries a sample may override the sample name via a 3rd element
    (``name_sum`` / ``name_count`` ride in their base family).
    """

    __slots__ = ("mtype", "name", "help", "samples")

    def __init__(self, mtype: str, name: str, help: str,
                 samples: Sequence[Tuple]):
        if mtype not in ("counter", "gauge", "summary", "untyped"):
            raise ValueError(f"unknown metric type {mtype!r}")
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.mtype = mtype
        self.name = name
        self.help = help
        self.samples = list(samples)


def summary_family(name: str, help: str, labels: Dict[str, Any],
                   window_snapshot: Dict[str, Optional[float]]
                   ) -> Optional[Family]:
    """A Prometheus summary from a :class:`LatencyWindow` snapshot
    (quantile samples in SECONDS + ``_sum``/``_count``); None when the
    window has seen nothing."""
    count = window_snapshot.get("count") or 0
    if not count:
        return None
    samples: List[Tuple] = []
    for q, key in (("0.5", "p50_ms"), ("0.9", "p90_ms"),
                   ("0.99", "p99_ms")):
        v = window_snapshot.get(key)
        if v is not None:
            samples.append(({**labels, "quantile": q}, v / 1e3))
    total_s = window_snapshot.get("total_s")
    if total_s is None:  # older snapshots: reconstruct from the mean
        mean_ms = window_snapshot.get("mean_ms") or 0.0
        total_s = mean_ms * count / 1e3
    samples.append((dict(labels), total_s, name + "_sum"))
    samples.append((dict(labels), count, name + "_count"))
    return Family("summary", name, help, samples)


class _Child:
    """One labeled time series of a counter/gauge family."""

    __slots__ = ("_family", "labels", "_value", "_callback")

    def __init__(self, family: "_LabeledFamily", labels: Dict[str, str]):
        self._family = family
        self.labels = labels
        self._value = 0.0
        self._callback: Optional[Callable[[], float]] = None

    def inc(self, by: float = 1.0):
        if self._family.mtype == "gauge":
            pass  # gauges may inc too
        elif by < 0:
            raise ValueError("counters only go up")
        with self._family._lock:
            self._value += by

    def set(self, value: float):
        if self._family.mtype != "gauge":
            raise TypeError("set() is gauge-only — counters only go up")
        with self._family._lock:
            self._value = float(value)
            self._callback = None

    def set_fn(self, fn: Callable[[], float]):
        """Lazy gauge: ``fn`` is called at scrape time (live-buffer
        counts, queue depths — values that exist, not accumulate)."""
        if self._family.mtype != "gauge":
            raise TypeError("set_fn() is gauge-only")
        self._callback = fn

    def get(self) -> float:
        if self._callback is not None:
            try:
                return float(self._callback())
            except Exception:
                return float("nan")
        with self._family._lock:
            return self._value


class _LabeledFamily:
    """A counter/gauge family: ``labels(**l)`` returns the per-series
    child (created on first use); label-less use goes through the
    default child."""

    def __init__(self, mtype: str, name: str, help: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.mtype = mtype
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], _Child] = {}

    def labels(self, **labels: Any) -> _Child:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self, dict(key))
                self._children[key] = child
            return child

    # label-less convenience
    def inc(self, by: float = 1.0):
        self.labels().inc(by)

    def set(self, value: float):
        self.labels().set(value)

    def set_fn(self, fn: Callable[[], float]):
        self.labels().set_fn(fn)

    def get(self, **labels: Any) -> float:
        return self.labels(**labels).get()

    def family(self) -> Family:
        with self._lock:
            children = list(self._children.values())
        return Family(self.mtype, self.name, self.help,
                      [(c.labels, c.get()) for c in children])


_PROCESS_START_UNIX = round(time.time(), 3)
_versions_cache: Optional[Dict[str, str]] = None


def _runtime_versions() -> Dict[str, str]:
    """jax/jaxlib versions, resolved lazily ONCE (importing jax at
    scrape time is free when the process already did; a jax-free
    process reports "none")."""
    global _versions_cache
    if _versions_cache is None:
        v = {"jax": "none", "jaxlib": "none"}
        try:
            import jax
            import jaxlib
            v = {"jax": jax.__version__, "jaxlib": jaxlib.__version__}
        except Exception:
            pass
        _versions_cache = v
    return _versions_cache


def process_info_family() -> Family:
    """``zoo_process_info`` — the info-gauge (constant 1, identity in
    the labels) every process exports by default: pid, distributed rank
    and supervisor incarnation (the PR 10 env contract), jax/jaxlib
    versions, and process start time.  The pod aggregator joins
    per-rank scrapes on it; a fleet debugger greps it first."""
    versions = _runtime_versions()
    labels = {
        "pid": str(os.getpid()),
        "rank": envcontract.env_str("ZOO_TPU_PROCESS_ID")
        or os.environ.get("JAX_PROCESS_ID") or "0",
        "incarnation": envcontract.env_str("ZOO_RESTART_COUNT", "0"),
        "jax": versions["jax"],
        "jaxlib": versions["jaxlib"],
        "start_unix": str(_PROCESS_START_UNIX),
    }
    return Family("gauge", "zoo_process_info",
                  "process identity info-gauge (labels carry the data)",
                  [(labels, 1.0)])


class MetricsRegistry:
    """The process-wide metric surface: owned counter/gauge families
    plus scrape-time collectors (module docstring).  Every registry
    exports ``zoo_process_info`` by default (``process_info=False``
    opts out) — the aggregator's join key must exist before anyone
    thinks to register it."""

    def __init__(self, process_info: bool = True):
        self._lock = threading.Lock()
        self._families: Dict[str, _LabeledFamily] = {}
        self._collectors: List[Callable[[], Iterable[Family]]] = []
        if process_info:
            self._collectors.append(lambda: [process_info_family()])

    def counter(self, name: str, help: str = "") -> _LabeledFamily:
        return self._family("counter", name, help)

    def gauge(self, name: str, help: str = "") -> _LabeledFamily:
        return self._family("gauge", name, help)

    def _family(self, mtype: str, name: str, help: str) -> _LabeledFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.mtype != mtype:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.mtype}, not {mtype}")
                return fam
            fam = _LabeledFamily(mtype, name, help)
            self._families[name] = fam
            return fam

    def register_collector(self, fn: Callable[[], Iterable[Family]]):
        """``fn()`` runs at every scrape and returns Family objects —
        the adapter for snapshot-oriented sources (registry metrics,
        tracer aggregates, XLA profile counters)."""
        with self._lock:
            self._collectors.append(fn)

    def collect(self) -> List[Family]:
        with self._lock:
            fams = [f.family() for f in self._families.values()]
            collectors = list(self._collectors)
        for fn in collectors:
            fams.extend(fn())
        return fams

    def render_prometheus(self) -> str:
        return render_prometheus(self.collect())

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every family (the non-Prometheus side of
        the same data)."""
        out: Dict[str, Any] = {}
        for fam in self.collect():
            series = []
            for s in fam.samples:
                labels, value = s[0], s[1]
                name = s[2] if len(s) > 2 else fam.name
                series.append({"name": name, "labels": dict(labels),
                               "value": value})
            out[fam.name] = {"type": fam.mtype, "help": fam.help,
                             "series": series}
        return out


# --------------------------------------------------------- exposition
def _escape_label_value(v: str) -> str:
    return (v.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v: Any) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(families: Iterable[Family]) -> str:
    """Prometheus text exposition format 0.0.4.  Families render in
    name order; every line is guaranteed to round-trip through
    :func:`parse_prometheus_text` (the CI smoke gate relies on it).

    Same-named families (e.g. one per model from independent
    collectors) are MERGED into one ``# TYPE`` block — real Prometheus
    parsers hard-reject duplicate TYPE lines, and our own lenient
    parser would never catch them; conflicting types for one name
    raise instead of shipping an invalid exposition."""
    merged: Dict[str, Family] = {}
    for fam in families:
        seen = merged.get(fam.name)
        if seen is None:
            merged[fam.name] = Family(fam.mtype, fam.name, fam.help,
                                      fam.samples)
        elif seen.mtype != fam.mtype:
            raise ValueError(
                f"metric {fam.name!r} collected as both "
                f"{seen.mtype} and {fam.mtype}")
        else:
            seen.samples.extend(fam.samples)
    lines: List[str] = []
    for fam in sorted(merged.values(), key=lambda f: f.name):
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.mtype}")
        for s in fam.samples:
            labels, value = s[0], s[1]
            name = s[2] if len(s) > 2 else fam.name
            if labels:
                body = ",".join(
                    f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{body}}} {_fmt_value(value)}")
            else:
                lines.append(f"{name} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?[0-9]+))?\s*$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|summary|histogram|untyped)$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")


def _parse_labels(body: str, line: str) -> Dict[str, str]:
    """Parse ``k="v",k2="v2"`` honoring backslash escapes."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        m = re.match(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"', body[i:])
        if not m:
            raise ValueError(
                f"unparseable exposition line (bad label segment at "
                f"offset {i}): {line!r}")
        key = m.group(1)
        i += m.end()
        out: List[str] = []
        while i < n:
            ch = body[i]
            if ch == "\\":
                if i + 1 >= n:
                    raise ValueError(
                        f"unparseable exposition line (dangling escape)"
                        f": {line!r}")
                nxt = body[i + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt))
                if out[-1] is None:
                    raise ValueError(
                        f"unparseable exposition line (bad escape "
                        f"\\{nxt}): {line!r}")
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                out.append(ch)
                i += 1
        else:
            raise ValueError(
                f"unparseable exposition line (unterminated label "
                f"value): {line!r}")
        labels[key] = "".join(out)
        rest = body[i:].lstrip()
        if rest.startswith(","):
            i = n - len(rest) + 1
        elif rest:
            raise ValueError(
                f"unparseable exposition line (junk after label "
                f"value): {line!r}")
        else:
            break
    return labels


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """The tiny stdlib parser the smoke gate round-trips the exposition
    through.  Returns ``{"samples": {(name, ((k,v),...)): value},
    "types": {...}, "helps": {...}}``; raises ``ValueError`` on any
    line that is not a valid comment, TYPE/HELP line, or sample."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line.strip():
            continue
        if line.startswith("#"):
            tm = _TYPE_RE.match(line)
            if tm:
                types[tm.group(1)] = tm.group(2)
                continue
            hm = _HELP_RE.match(line)
            if hm:
                helps[hm.group(1)] = hm.group(2)
                continue
            if line.startswith("# TYPE") or line.startswith("# HELP"):
                raise ValueError(
                    f"unparseable exposition line (malformed TYPE/HELP)"
                    f": {line!r}")
            continue  # free-form comment: legal, meaningless
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        labels = (_parse_labels(m.group("labels"), line)
                  if m.group("labels") else {})
        value_s = m.group("value")
        try:
            value = float(value_s)
        except ValueError:
            if value_s in ("+Inf", "-Inf", "NaN"):
                value = float(value_s.replace("Inf", "inf")
                              .replace("NaN", "nan"))
            else:
                raise ValueError(
                    f"unparseable exposition line (bad value "
                    f"{value_s!r}): {line!r}")
        key = (m.group("name"), tuple(sorted(labels.items())))
        samples[key] = value
    return {"samples": samples, "types": types, "helps": helps}
