"""Fleet-wide distributed tracing: cross-process span stitching,
per-request time attribution, and the offline waterfall CLI.

One served request crosses at least two processes — the fleet router's
span (``route_pick`` -> ``worker_call``) and the worker registry's span
(``admission_queue`` -> ... -> ``execute``) — and after a retry or a
pager cold fault, three.  Each process records its half faithfully
(router: tracer ring; worker: tracer ring + flight recorder), but a p99
investigation needs them JOINED.  This module owns both joins:

**Inline stitching** (the hot half).  A worker reply whose request
carried a ``trace_id`` piggybacks a compact summary of the worker-side
span — :func:`reply_trace`, riding the same per-reply discipline as the
``load`` residency piggyback — and the router nests it under its open
``worker_call`` phase via :func:`nest_summary`.  The router span then
knows, per request, how much of ``worker_call`` the worker actually
accounts for; the remainder is the *unattributed wire+queue gap*
(:func:`inline_gap_ms`), surfaced as ``info["fleet_gap_ms"]``.

**Offline assembly** (the postmortem half)::

    python -m analytics_zoo_tpu.observability.tracefleet FLIGHT_DIR \
        --router ring.json --trace ID

harvests every rank's flight-recorder span records (ALL incarnations —
a retried request's first leg lives in the incarnation that was
SIGKILLed, which :func:`flightrec.harvest`'s newest-only policy would
skip), joins them with the router tracer ring (:func:`dump_ring` /
``GET /traces`` JSON) on ``trace_id``, aligns clocks through each
rank's ``meta.json`` wall/monotonic anchor, and renders a waterfall.
``--postmortem pod_postmortem.json`` reads the rank spans out of a
supervisor postmortem instead — the path that still works when the
flight-recorder directory is gone and only the incident file survived.

Clock alignment: a rank's leg is placed at ``anchor.unix +
(span.start_mono_s - anchor.mono)`` — one wall-clock trust point per
incarnation instead of one per span.  A leg that still lands outside
its ``worker_call`` occurrence (wall-clock skew between hosts) is
shifted by the minimal correction that fits it inside, and that
correction is REPORTED per ``rank{r}.i{i}`` in ``skew_s`` — the
stitched timeline is monotonic by construction, and the operator sees
exactly how much the clocks disagreed.

Attribution: ``attributed_fraction`` counts router phases other than
``worker_call``, every stitched leg's phase total, the named
``fleet_gap`` remainder of each stitched occurrence, and — on a
retried request — the failed (non-final) ``worker_call`` occurrence,
whose worker died without replying.  What is NOT counted is exactly
the time no process can name: a missing leg on a non-retried
occurrence makes the trace ``partial`` and drags the fraction down
honestly instead of papering over the hole.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import flightrec
from . import trace as _trace_mod

#: the router phase a worker leg nests under
_SUMMARY_PHASE = "worker_call"
#: alignment tolerance: a leg within this of its occurrence counts as
#: fitting (same-host perf_counter/time() jitter, rounding in to_dict)
_EPS_MS = 1.0


# --------------------------------------------------------- inline half
def span_summary(span_dict: Dict[str, Any],
                 rank: Optional[int] = None,
                 inc: Optional[int] = None) -> Dict[str, Any]:
    """The compact piggyback form of a finished span dict: closed
    phases as ``[name, start_ms, dur_ms]`` triples plus the wall/mono
    anchors the stitcher aligns on.  Events, labels and the span name
    are dropped and floats are rounded to 1us — the full tree stays in
    the worker's ring/flight recorder; the reply carries only what
    per-request attribution needs, and every extra byte here is paid
    on the hot serve path (the traced/untraced throughput-ratio bench
    gate prices this function)."""
    phases = [[p.get("name"), round(p.get("start_ms") or 0.0, 3),
               round(p["dur_ms"], 3)]
              for p in (span_dict.get("phases") or ())
              if isinstance(p, dict) and p.get("dur_ms") is not None]
    wall = span_dict.get("wall_ms")
    unix = span_dict.get("start_unix_s")
    mono = span_dict.get("start_mono_s")
    out: Dict[str, Any] = {
        "tid": span_dict.get("trace_id"),
        "wall_ms": None if wall is None else round(wall, 3),
        "start_unix_s": None if unix is None else round(unix, 6),
        "start_mono_s": None if mono is None else round(mono, 6),
        "phases": phases,
    }
    if rank is not None:
        out["rank"] = rank
    if inc is not None:
        out["inc"] = inc
    return out


def summary_wire(span, rank: Optional[int] = None,
                 inc: Optional[int] = None) -> str:
    """The summary of a finished live :class:`Span` as ONE compact
    delimited string: ``tid|wall_ms|unix|mono|rank|inc|ph:s:d,...``
    (empty field = None).  A single string rides the binary wire as
    one leaf — the recursive envelope encode/decode walk, the JSON
    float reprs, and the dict rebuilds all priced out against the
    traced/untraced throughput gate; this form costs one format call
    per side.  Built straight off the Span (no ``to_dict``)."""
    ph = ",".join(
        f"{n}:{(t0 - span.start_s) * 1e3:.3f}:{(t1 - t0) * 1e3:.3f}"
        for n, t0, t1 in span.phases if t1 is not None)
    return (f"{span.trace_id}|{span.wall_s * 1e3:.3f}|"
            f"{span.start_wall:.6f}|{span.start_s:.6f}|"
            f"{'' if rank is None else rank}|"
            f"{'' if inc is None else inc}|{ph}")


def parse_summary(wire: str) -> Optional[Dict[str, Any]]:
    """A :func:`summary_wire` string back into the summary-dict shape
    (:func:`span_summary`); None for anything malformed — the router
    must nest nothing rather than fail a request over a bad peer."""
    try:
        tid, wall, unix, mono, rank, inc, ph = wire.split("|")
        phases: List[List[Any]] = []
        if ph:
            for p in ph.split(","):
                name, start, dur = p.rsplit(":", 2)
                phases.append([name, float(start), float(dur)])
        out: Dict[str, Any] = {
            "tid": tid or None,
            "wall_ms": float(wall) if wall else None,
            "start_unix_s": float(unix) if unix else None,
            "start_mono_s": float(mono) if mono else None,
            "phases": phases,
            "_phase": _SUMMARY_PHASE,
        }
        if rank:
            out["rank"] = int(rank)
        if inc:
            out["inc"] = int(inc)
        return out
    except (ValueError, AttributeError):
        return None


# Span.to_dict renders raw wire-string children through this module's
# parser — registered at import, which every string-nesting process
# (the router) reaches via nest_summary itself
_trace_mod.set_child_decoder(parse_summary)


def reply_trace(tracer, trace_id: Optional[str],
                rank: Optional[int] = None,
                inc: Optional[int] = None) -> Optional[str]:
    """Worker-side piggyback builder (a zoolint hot entry): the wire
    summary of THIS request's just-finished registry span, or None
    when the request was untraced — the untraced reply pays one
    ``is None`` branch and nothing else."""
    if tracer is None or trace_id is None:
        return None
    span = tracer.find_span(trace_id)
    if span is None:
        return None
    return summary_wire(span, rank=rank, inc=inc)


def nest_summary(span, summary) -> None:
    """Router-side inline stitch (a zoolint hot entry): nest a reply's
    worker-span summary — the :func:`summary_wire` string, or an
    already-parsed dict — under the router span's ``worker_call``.
    A wire string is stored RAW (one object; parsed lazily at
    serialization — per-request parsing allocated enough to show up
    as gc pauses against the traced-throughput gate).  Tolerant of
    anything a peer sends: a missing or malformed piggyback nests
    nothing, never fails the request."""
    if span is None:
        return
    if isinstance(summary, str):
        if summary.count("|") == 6:  # shape sniff, no allocation
            span.add_child(summary)
        return
    if not isinstance(summary, dict):
        return
    span.add_child({**summary, "_phase": _SUMMARY_PHASE})


def inline_gap_ms(span) -> Optional[float]:
    """Per-request unattributed wire+queue gap: the span's total
    ``worker_call`` time minus the wall time its nested worker legs
    account for (>= 0; None when nothing is nested)."""
    children = getattr(span, "children", None)
    if not children:
        return None
    tot = span.phase_totals().get(_SUMMARY_PHASE)
    if tot is None:
        return None
    worker_ms = 0.0
    for ch in children:
        try:
            if isinstance(ch, str):
                # raw wire child: wall_ms is field 2 — one bounded
                # split, no full parse on the serve path
                worker_ms += float(ch.split("|", 2)[1])
            else:
                worker_ms += float(ch.get("wall_ms") or 0.0)
        except (TypeError, ValueError, IndexError):
            pass
    return round(max(tot * 1e3 - worker_ms, 0.0), 4)


# -------------------------------------------------------- offline half
def iter_rank_dirs(base_dir: str) -> List[Tuple[int, int, str]]:
    """Every ``rank{r}.i{i}`` recorder directory under ``base_dir`` —
    ALL incarnations, sorted — unlike :func:`flightrec.harvest`'s
    newest-incarnation policy: a retried request's first leg lives in
    the incarnation that died."""
    out: List[Tuple[int, int, str]] = []
    try:
        names = os.listdir(base_dir)
    except OSError:
        return out
    for name in names:
        if not name.startswith("rank") or ".i" not in name:
            continue
        try:
            rank_s, inc_s = name[4:].split(".i", 1)
            rank, inc = int(rank_s), int(inc_s)
        except ValueError:
            continue
        full = os.path.join(base_dir, name)
        if os.path.isdir(full):
            out.append((rank, inc, full))
    out.sort()
    return out


def harvest_legs(base_dir: str,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every span record under ``base_dir`` (optionally filtered to
    one ``trace_id``) as stitchable legs ``{rank, inc, anchor, span}``.
    Torn segment tails, missing directories, and anchor-less metas all
    degrade to fewer/less-aligned legs, never an exception."""
    legs: List[Dict[str, Any]] = []
    for rank, inc, d in iter_rank_dirs(base_dir):
        meta: Dict[str, Any] = {}
        try:
            with open(os.path.join(d, flightrec._META)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        anchor = meta.get("anchor")
        if not isinstance(anchor, dict):
            anchor = None
        records = (
            flightrec.read_records(os.path.join(d, flightrec._SEGMENT_OLD))
            + flightrec.read_records(os.path.join(d, flightrec._SEGMENT)))
        for r in records:
            if r.get("t") != "span":
                continue
            span = r.get("span")
            if not isinstance(span, dict):
                continue
            if trace_id is not None and span.get("trace_id") != trace_id:
                continue
            legs.append({"rank": rank, "inc": inc,
                         "anchor": anchor, "span": span})
    return legs


def legs_from_postmortem(pm: Dict[str, Any],
                         trace_id: Optional[str] = None
                         ) -> List[Dict[str, Any]]:
    """Stitchable legs out of a supervisor postmortem's per-rank
    harvest — the source that survives when the SIGKILLed worker's
    directory itself is gone."""
    legs: List[Dict[str, Any]] = []
    for rank_s, rec in (pm.get("ranks") or {}).items():
        if not isinstance(rec, dict):
            continue
        meta = rec.get("meta") if isinstance(rec.get("meta"), dict) else {}
        anchor = meta.get("anchor")
        if not isinstance(anchor, dict):
            anchor = None
        try:
            rank: Any = int(rank_s)
        except (TypeError, ValueError):
            rank = rank_s
        inc = rec.get("incarnation", meta.get("incarnation", 0))
        for span in rec.get("spans") or ():
            if not isinstance(span, dict):
                continue
            if trace_id is not None and span.get("trace_id") != trace_id:
                continue
            legs.append({"rank": rank, "inc": inc,
                         "anchor": anchor, "span": span})
    return legs


def _summary_span(ch: Dict[str, Any]) -> Dict[str, Any]:
    """An inline piggyback summary re-shaped as a full span dict —
    the stitcher's fallback legs when the flight recorder is gone but
    the router span still carries its nested children."""
    return {"trace_id": ch.get("tid"), "name": ch.get("name"),
            "labels": dict(ch.get("labels") or {}),
            "start_unix_s": ch.get("start_unix_s"),
            "start_mono_s": ch.get("start_mono_s"),
            "wall_ms": ch.get("wall_ms"),
            "coverage": ch.get("coverage"),
            "phases": ch.get("phases") or []}


def legs_from_children(router_span: Dict[str, Any]
                       ) -> List[Dict[str, Any]]:
    return [{"rank": ch.get("rank"), "inc": ch.get("inc", 0),
             "anchor": None, "span": _summary_span(ch)}
            for ch in router_span.get("children") or ()
            if isinstance(ch, dict)]


def _phase_triples(phases) -> Iterator[Tuple[str, float, Optional[float]]]:
    """Normalize either phase shape — ``to_dict`` dicts or piggyback
    ``[name, start_ms, dur_ms]`` triples — skipping anything
    malformed."""
    for p in phases or ():
        if isinstance(p, dict):
            name, start, dur = p.get("name"), p.get("start_ms"), \
                p.get("dur_ms")
        elif isinstance(p, (list, tuple)) and len(p) >= 3:
            name, start, dur = p[0], p[1], p[2]
        else:
            continue
        if name is None or start is None:
            continue
        try:
            start = float(start)
        except (TypeError, ValueError):
            continue
        if dur is not None:
            try:
                dur = float(dur)
            except (TypeError, ValueError):
                dur = None
        yield str(name), start, dur


def _leg_abs_start(leg: Dict[str, Any]) -> Optional[float]:
    """Wall-clock start of a leg: the rank's meta anchor + the span's
    monotonic start when both exist (ONE trusted wall reading per
    incarnation), else the span's own wall stamp; None when the leg
    carries no time basis at all (it is then placed by fit alone and
    reports no skew)."""
    span = leg.get("span") or {}
    anchor = leg.get("anchor") or {}
    mono = span.get("start_mono_s")
    try:
        if mono is not None and "unix" in anchor and "mono" in anchor:
            return float(anchor["unix"]) \
                + (float(mono) - float(anchor["mono"]))
        unix = span.get("start_unix_s")
        return float(unix) if unix else None
    except (TypeError, ValueError):
        return None


def _fit_shift(leg_start_s: float, leg_dur_s: float,
               occ_start_s: float, occ_dur_s: float) -> float:
    """Minimal time shift (seconds) that places the leg inside the
    occurrence window; 0 when it already fits, the centering shift
    when the leg cannot fit (leg longer than the occurrence)."""
    lo = occ_start_s - leg_start_s
    hi = (occ_start_s + occ_dur_s) - (leg_start_s + leg_dur_s)
    if lo <= 0.0 <= hi:
        return 0.0
    if lo > hi:  # leg longer than occurrence: center it
        return (lo + hi) / 2.0
    return lo if lo > 0.0 else hi


def stitch(router_span: Optional[Dict[str, Any]],
           legs: List[Dict[str, Any]],
           trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Join one router span with its worker legs into a monotonic
    waterfall (module docstring for alignment and attribution rules).
    Degrades: no router half, no legs, torn legs, anchor-less metas
    all yield a ``partial`` trace, never an exception."""
    R = router_span if isinstance(router_span, dict) else {}
    wall_ms = float(R.get("wall_ms") or 0.0)
    labels = dict(R.get("labels") or {})
    retried = bool(labels.get("retried"))

    entries = []
    for leg in legs or ():
        if isinstance(leg, dict) and isinstance(leg.get("span"), dict):
            entries.append((_leg_abs_start(leg), leg))
    # timeless legs (no basis) sort last and are placed by fit alone
    entries.sort(key=lambda e: (e[0] is None, e[0] or 0.0))

    base = float(R.get("start_unix_s") or 0.0)
    if not R:
        timed = [s for s, _ in entries if s is not None]
        if timed:
            base = timed[0]

    rows: List[Dict[str, Any]] = []
    occs: List[Dict[str, Any]] = []
    attributed_ms = 0.0
    for name, start, dur in _phase_triples(R.get("phases")):
        if dur is None:  # open at finish: extend to span end
            dur = max(wall_ms - start, 0.0)
        rows.append({"src": "router", "phase": name,
                     "start_ms": round(start, 4),
                     "dur_ms": round(dur, 4)})
        if name == _SUMMARY_PHASE:
            occs.append({"start_ms": start, "dur_ms": dur,
                         "leg": None, "shift_s": 0.0})
        else:
            attributed_ms += dur

    # greedy time-order matching: each leg takes the free occurrence
    # it FITS (duration-wise) needing the smallest correction — the
    # fit test first, because under forged clocks every candidate
    # shift is ~the clock error and the leg must not be centered into
    # an occurrence shorter than itself when a fitting one is free
    # (two legs of a retried request land on their own occurrences)
    unmatched_legs: List[Dict[str, Any]] = []
    for start_abs, leg in entries:
        leg_dur_s = float((leg["span"].get("wall_ms") or 0.0)) / 1e3
        best = None
        best_key = (True, 0.0)
        best_shift = 0.0
        best_rel = 0.0
        for occ in occs:
            if occ["leg"] is not None:
                continue
            rel = ((start_abs - base) if start_abs is not None
                   else occ["start_ms"] / 1e3)
            shift = _fit_shift(rel, leg_dur_s,
                               occ["start_ms"] / 1e3,
                               occ["dur_ms"] / 1e3)
            fits = leg_dur_s <= occ["dur_ms"] / 1e3 + _EPS_MS / 1e3
            key = (not fits, abs(shift))
            if best is None or key < best_key:
                best, best_key = occ, key
                best_shift, best_rel = shift, rel
        if best is None:
            unmatched_legs.append(leg)
            continue
        best["leg"] = leg
        best["shift_s"] = best_shift
        best["leg_rel_s"] = best_rel
        best["timeless"] = start_abs is None

    gap_ms = 0.0
    skew: Dict[str, float] = {}
    monotonic = True
    stitched = 0
    missing = 0
    for i, occ in enumerate(occs):
        leg = occ["leg"]
        if leg is None:
            if retried and i < len(occs) - 1:
                # the failed leg of a retried request: the worker died
                # without replying — the router's own measurement of
                # that occurrence is the attribution
                rows.append({"src": "wire", "phase": "worker_call_failed",
                             "start_ms": round(occ["start_ms"], 4),
                             "dur_ms": round(occ["dur_ms"], 4)})
                attributed_ms += occ["dur_ms"]
            else:
                missing += 1
            continue
        stitched += 1
        span = leg["span"]
        shift = occ["shift_s"]
        if not occ.get("timeless") and abs(shift) > _EPS_MS / 1e3:
            key = f"rank{leg.get('rank')}.i{leg.get('inc', 0)}"
            if key not in skew or abs(shift) > abs(skew[key]):
                skew[key] = round(shift, 6)
        leg_start_ms = (occ["leg_rel_s"] + shift) * 1e3
        leg_wall = float(span.get("wall_ms") or 0.0)
        src = f"rank{leg.get('rank')}"
        leg_total = 0.0
        for name, start, dur in _phase_triples(span.get("phases")):
            if dur is None:
                continue
            rows.append({"src": src, "phase": name,
                         "start_ms": round(leg_start_ms + start, 4),
                         "dur_ms": round(dur, 4)})
            leg_total += dur
        attributed_ms += leg_total
        gap = max(occ["dur_ms"] - leg_wall, 0.0)
        gap_ms += gap
        attributed_ms += gap
        rows.append({"src": "wire", "phase": "fleet_gap",
                     "start_ms": round(occ["start_ms"], 4),
                     "dur_ms": round(gap, 4)})
        if leg_start_ms < occ["start_ms"] - _EPS_MS \
                or leg_start_ms + leg_wall \
                > occ["start_ms"] + occ["dur_ms"] + _EPS_MS:
            monotonic = False

    # legs that found no occurrence (router half missing, or more
    # legs than worker_call occurrences) still render — at their own
    # claimed offsets — so a router-less postmortem shows SOMETHING
    for leg in unmatched_legs:
        span = leg["span"]
        start_abs = _leg_abs_start(leg)
        leg_start_ms = 0.0 if start_abs is None \
            else (start_abs - base) * 1e3
        src = f"rank{leg.get('rank')}"
        for name, start, dur in _phase_triples(span.get("phases")):
            if dur is None:
                continue
            rows.append({"src": src, "phase": name,
                         "start_ms": round(leg_start_ms + start, 4),
                         "dur_ms": round(dur, 4)})
    rows.sort(key=lambda r: (r["start_ms"], -r["dur_ms"]))

    frac = min(attributed_ms / wall_ms, 1.0) if wall_ms > 0 else 0.0
    return {
        "trace_id": R.get("trace_id") or trace_id,
        "name": R.get("name"),
        "labels": labels,
        "start_unix_s": base,
        "wall_ms": wall_ms,
        "rows": rows,
        "occurrences": len(occs),
        "stitched_legs": stitched,
        "gap_ms": round(gap_ms, 4),
        "attributed_ms": round(attributed_ms, 4),
        "attributed_fraction": round(frac, 4),
        "skew_s": skew,
        "monotonic": monotonic,
        "partial": (not R) or missing > 0 or bool(unmatched_legs),
    }


def assemble(trace_id: str,
             router_spans: List[Dict[str, Any]],
             legs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One trace_id's stitched view from already-loaded sources.  The
    newest router span wins; with no flight-recorder legs, the router
    span's own inline children (when it has them) are the fallback."""
    R = None
    for sd in router_spans or ():
        if isinstance(sd, dict) and sd.get("trace_id") == trace_id:
            R = sd
    mine = [leg for leg in legs or ()
            if (leg.get("span") or {}).get("trace_id") == trace_id]
    if R is not None and not mine:
        mine = legs_from_children(R)
    return stitch(R, mine, trace_id=trace_id)


def dump_ring(tracer, path: str) -> str:
    """Persist a router tracer's ring + exemplar index as the CLI's
    ``--router`` input (atomic write; survives anything that happens
    to the router process afterwards)."""
    payload = {"written_unix": round(time.time(), 6),
               "spans": tracer.recent(),
               "exemplars": (tracer.exemplars()
                             if hasattr(tracer, "exemplars") else [])}
    flightrec.atomic_write(path, json.dumps(payload, default=str))
    return path


def load_router_spans(path: str) -> List[Dict[str, Any]]:
    """Router span dicts from a :func:`dump_ring` file, a bare JSON
    list of spans, or a ``GET /traces`` response body."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(data, list):
        return [d for d in data if isinstance(d, dict)]
    if isinstance(data, dict):
        spans = data.get("spans") or data.get("traces") or []
        return [d for d in spans if isinstance(d, dict)]
    return []


# --------------------------------------------------------------- render
def render_waterfall(st: Dict[str, Any], width: int = 44) -> str:
    labels = st.get("labels") or {}
    head = f"trace {st.get('trace_id')} {st.get('name') or '?'}"
    if labels.get("model"):
        head += f" model={labels['model']}"
    head += (f" wall={float(st.get('wall_ms') or 0.0):.2f}ms"
             f" attributed="
             f"{100.0 * float(st.get('attributed_fraction') or 0.0):.1f}%"
             f" gap={float(st.get('gap_ms') or 0.0):.2f}ms")
    if st.get("partial"):
        head += " PARTIAL"
    lines = [head]
    if st.get("skew_s"):
        lines.append("  clock skew corrected: " + ", ".join(
            f"{k}={v:+.3f}s" for k, v in sorted(st["skew_s"].items())))
    rows = st.get("rows") or []
    span_ms = max([float(st.get("wall_ms") or 0.0)]
                  + [r["start_ms"] + r["dur_ms"] for r in rows])
    for r in rows:
        if span_ms > 0:
            a = min(int(width * max(r["start_ms"], 0.0) / span_ms),
                    width - 1)
            b = max(int(round(width * r["dur_ms"] / span_ms)), 1)
            bar = "." * a + "#" * min(b, width - a)
        else:
            bar = ""
        lines.append(f"  {str(r['src']):>8}  {r['phase']:<22}"
                     f"{r['start_ms']:>10.2f} {r['dur_ms']:>9.2f}ms  "
                     f"{bar}")
    return "\n".join(lines)


def _join_index(router_spans: List[Dict[str, Any]],
                legs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    idx: Dict[str, Dict[str, Any]] = {}
    for sd in router_spans:
        tid = sd.get("trace_id")
        if tid:
            idx[tid] = {"trace_id": tid, "router": True, "legs": 0,
                        "ranks": set(),
                        "wall_ms": sd.get("wall_ms"),
                        "labels": sd.get("labels") or {}}
    for leg in legs:
        tid = (leg.get("span") or {}).get("trace_id")
        if not tid:
            continue
        row = idx.setdefault(tid, {"trace_id": tid, "router": False,
                                   "legs": 0, "ranks": set(),
                                   "wall_ms": None, "labels": {}})
        row["legs"] += 1
        row["ranks"].add(leg.get("rank"))
    out = list(idx.values())
    for row in out:
        row["ranks"] = sorted(r for r in row["ranks"] if r is not None)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.observability.tracefleet",
        description="Stitch one request's cross-process spans into a "
                    "waterfall: router tracer ring + per-rank flight-"
                    "recorder records, joined on trace_id, clocks "
                    "aligned via each rank's meta.json anchor")
    ap.add_argument("dir", nargs="?", default=None,
                    help="fleet flight-recorder dir "
                         "(ZOO_FLIGHTREC_DIR; rank{r}.i{i}/ layout)")
    ap.add_argument("--router", metavar="FILE", default=None,
                    help="router tracer ring dump "
                         "(tracefleet.dump_ring / GET /traces JSON)")
    ap.add_argument("--postmortem", metavar="FILE", default=None,
                    help="pod/worker postmortem JSON as the rank-span "
                         "source (works after SIGKILL, no live dir "
                         "needed)")
    ap.add_argument("--trace", metavar="ID", default=None,
                    help="trace_id to stitch (default: list joinable "
                         "traces)")
    ap.add_argument("--list", action="store_true",
                    help="list joinable trace_ids and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the stitched trace as JSON")
    args = ap.parse_args(argv)
    if not args.dir and not args.postmortem:
        ap.error("need a flight-recorder DIR and/or --postmortem FILE")

    router_spans = load_router_spans(args.router) if args.router else []
    legs: List[Dict[str, Any]] = []
    if args.dir:
        legs.extend(harvest_legs(args.dir))
    if args.postmortem:
        try:
            with open(args.postmortem) as f:
                pm = json.load(f)
        except (OSError, ValueError) as e:
            print(f"unreadable postmortem: {e}", file=sys.stderr)
            return 2
        legs.extend(legs_from_postmortem(pm))

    if args.list or not args.trace:
        rows = _join_index(router_spans, legs)
        rows.sort(key=lambda r: (not r["router"], -r["legs"]))
        for row in rows[:64]:
            labels = row["labels"]
            print(f"{row['trace_id']}  router={'y' if row['router'] else 'n'}"
                  f"  legs={row['legs']} ranks={row['ranks']}"
                  + (f" wall={row['wall_ms']}ms"
                     if row["wall_ms"] is not None else "")
                  + (f" model={labels.get('model')}"
                     if labels.get("model") else ""))
        if len(rows) > 64:
            print(f"... {len(rows) - 64} more")
        if not rows:
            print("(no joinable spans found)")
        return 0

    st = assemble(args.trace, router_spans, legs)
    if args.json:
        print(json.dumps(st, indent=2, default=str))
    else:
        print(render_waterfall(st))
    return 0


if __name__ == "__main__":
    sys.exit(main())
