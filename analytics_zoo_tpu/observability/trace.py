"""Per-request tracing for the serving stack: Span / Tracer with
explicit cross-thread handoff.

A request through the serving plane hops five queues/threads (admission
-> coalescer queue -> dispatcher -> device -> fan-out), so a p99
regression is unattributable from endpoint latency alone.  Each request
carries ONE :class:`Span` recording a contiguous sequence of phases::

    admission_queue -> coalesce_wait -> pad -> device_put -> execute
                    -> depad

``phase_start`` closes the previously open phase at the same timestamp,
so phases are gap-free BY CONSTRUCTION — the only uncovered time is the
tail between the last ``phase_end`` and ``finish()`` (future wake-up +
response serialization), which ``coverage`` exposes.

Cross-thread handoff is EXPLICIT: contextvars do not propagate into the
coalescer's dispatcher thread (it was started long before the request
existed), so the pending request object carries its span and the
dispatcher calls ``phase_start`` on it directly.  A span is only ever
touched by one thread at a time (caller until submit, dispatcher until
the future resolves, caller again after), so spans need no lock.

Cost model: when no tracer is active, the hot path pays ONE module-flag
branch (``current_span()`` returns None immediately); instrumentation
sites guard every other call behind ``if span is not None``.

Finished spans land in the tracer's bounded ring buffer (``recent()``)
and their per-phase durations aggregate into ``phase_stats()`` /
``families()`` for Prometheus exposition.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from .. import envcontract
from .metrics import Family

#: the canonical request phase order (docs/observability.md).  After
#: admission come the weight pager's cold-start phases (absent on the
#: resident hot path): pager_wait parks behind an in-flight fault,
#: weights_h2d is the one device_put of the host weights, and
#: exec_rehydrate the execstore warmup of the bucket ladder.  Then the
#: one-shot predict chain; the last three belong to the
#: continuous-batching generate path (decode_wait covers the engine
#: queue, prefill the bucketed prompt pass + slot insert, decode_step
#: the whole shared-step participation until eviction).
PHASES = ("admission_queue", "pager_wait", "weights_h2d",
          "exec_rehydrate", "coalesce_wait", "pad", "device_put",
          "execute", "depad", "decode_wait", "prefill", "decode_step")

#: the training-step phase order (train/stepprof.py; same gap-free
#: discipline as the request chain): waiting on the prefetch queue,
#: the host->device upload (measured on the prefetch thread and
#: attributed to the consuming step), the host-side microbatch split
#: when gradient accumulation is on (also prefetch-thread-measured),
#: the compiled step dispatch, and the checkpoint save when its
#: trigger fires.
TRAIN_PHASES = ("data_wait", "h2d", "grad_accum", "step_compute",
                "ckpt_save")

_SPAN_VAR: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("zoo_tpu_span", default=None)
# STICKY enable flag: False until the first span is ever activated in
# this process, True forever after.  A process that never traces pays
# exactly one bool branch per predict; once tracing has happened the
# branch falls through to a contextvar read (~100ns).  Sticky (rather
# than refcounted) keeps activate() lock-free on the request path —
# the bench overhead gate measures this.
_ENABLED = False


def tracing_active() -> bool:
    """True once any span has ever been activated in this process
    (sticky — see the flag comment above)."""
    return _ENABLED


def current_span() -> "Optional[Span]":
    """The span activated on this thread's context, or None.  Before
    any tracing has happened the path is one global-flag branch — no
    contextvar read."""
    if not _ENABLED:
        return None
    return _SPAN_VAR.get()


@contextlib.contextmanager
def activate(span: "Optional[Span]"):
    """Make ``span`` the current span for the calling thread (and any
    code it calls synchronously).  Thread hops do NOT inherit it — hand
    the span object across explicitly (the coalescer's pending request
    carries it)."""
    global _ENABLED
    if span is None:
        yield None
        return
    token = _SPAN_VAR.set(span)
    if not _ENABLED:
        _ENABLED = True
    try:
        yield span
    finally:
        _SPAN_VAR.reset(token)


# a fresh uuid4 per request costs ~40us on small hosts — material
# against a ~1ms request (the bench overhead gate caught it).  One
# random prefix per process + a GIL-atomic counter is unique within
# any ring/log scope and ~1us.
_ID_PREFIX = uuid.uuid4().hex[:8]
_ID_COUNTER = itertools.count(1)


def new_trace_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_COUNTER) & 0xffffffff:08x}"


# finished-span sink for the flight recorder (flightrec.configure):
# every tracer-owned span that finishes is offered to it.  One None
# check per finish when no recorder is configured.
_FINISH_HOOK: "Optional[Any]" = None


def set_finish_hook(fn) -> None:
    global _FINISH_HOOK
    _FINISH_HOOK = fn


# decoder for compact wire-string children (set by tracefleet on
# import — the module that owns the wire format): Span.to_dict uses
# it to render raw nested strings as summary dicts.  Returning None
# for a given string drops that child from the serialized form.
_CHILD_DECODER: "Optional[Any]" = None


def set_child_decoder(fn) -> None:
    global _CHILD_DECODER
    _CHILD_DECODER = fn


def tail_config_from_env() -> Dict[str, Any]:
    """Tail-sampling Tracer kwargs from the env contract:
    ``ZOO_TRACE_TAIL_Q`` (retention quantile, default 0.95; a value
    outside (0,1) — e.g. an explicit ``0`` — disables retention) and
    ``ZOO_TRACE_TAIL_CAP`` (exemplar budget, default 64).  Garbage
    degrades to the defaults, the envcontract parsing discipline."""
    cap = envcontract.env_int("ZOO_TRACE_TAIL_CAP", 64)
    raw = envcontract.env_str("ZOO_TRACE_TAIL_Q")
    q: Optional[float] = 0.95
    if raw is not None:
        try:
            q = float(raw)
        except ValueError:
            q = 0.95
        if not (0.0 < q < 1.0):
            q = None
    return {"tail_quantile": q, "tail_cap": max(cap, 1)}


class Span:
    """One request's timeline: ordered phases + point events + labels.

    Single-owner-at-a-time by design (see module doc) — no lock."""

    __slots__ = ("name", "trace_id", "labels", "start_s", "start_wall",
                 "end_s", "phases", "events", "children", "_open",
                 "_tracer", "_totals")

    def __init__(self, tracer: "Optional[Tracer]", name: str,
                 trace_id: Optional[str] = None,
                 labels: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        # taken by reference, not copied: every caller passes a fresh
        # **labels dict, and the copy showed up in the overhead gate
        self.labels: Dict[str, Any] = labels if labels is not None else {}
        self.start_s = time.perf_counter()
        self.start_wall = time.time()
        self.end_s: Optional[float] = None
        # each entry: [phase_name, start, end_or_None]
        self.phases: List[List[Any]] = []
        self.events: List[Dict[str, Any]] = []
        # remote child summaries (add_child); None until the first one
        # lands — almost no span has children, so no list allocation
        self.children: Optional[List[Dict[str, Any]]] = None
        self._open: Optional[List[Any]] = None
        self._totals: Optional[Dict[str, float]] = None

    # ---- phases ----
    def phase_start(self, name: str):
        """Open phase ``name``; the previously open phase (if any) is
        closed at the SAME timestamp, so consecutive phases never gap."""
        t = time.perf_counter()
        if self._open is not None:
            self._open[2] = t
        p = [name, t, None]
        self.phases.append(p)
        self._open = p

    def phase_end(self):
        """Close the open phase (idempotent when none is open)."""
        if self._open is not None:
            self._open[2] = time.perf_counter()
            self._open = None

    def phase_add(self, name: str, seconds: float,
                  end_s: Optional[float] = None):
        """Record an already-measured CLOSED phase (duration known, no
        open/close bracketing).  For work measured on another thread —
        the prefetch thread's h2d upload — whose duration belongs in
        this span's totals but whose wall interval overlaps the
        on-thread phases."""
        end = time.perf_counter() if end_s is None else end_s
        self.phases.append([name, end - seconds, end])

    @contextlib.contextmanager
    def phase(self, name: str):
        self.phase_start(name)
        try:
            yield self
        finally:
            self.phase_end()

    # ---- events / labels ----
    def event(self, name: str, **attrs: Any):
        """A point-in-time annotation (e.g. an XLA ``backend_compile``
        observed while this span was current)."""
        self.events.append({"name": name,
                            "t_s": time.perf_counter() - self.start_s,
                            **attrs})

    def set_label(self, key: str, value: Any):
        self.labels[key] = value

    def add_child(self, child):
        """Nest a REMOTE span summary under this span — the fleet
        router attaches the worker-side timeline a reply piggybacked
        (tracefleet.py owns the summary shape and the stitching).  A
        child is either a summary dict or the RAW compact wire string
        it arrived as: the string is stored un-parsed — one object —
        and only decoded when the span is serialized, because parsing
        per request allocated enough to show up as gc pauses against
        the traced-throughput gate."""
        if self.children is None:
            self.children = []
        self.children.append(child)

    # ---- lifecycle ----
    def finish(self):
        """Close the open phase, stamp the end, and hand the span to
        its tracer's ring buffer / aggregates (idempotent)."""
        if self.end_s is not None:
            return
        self.phase_end()
        self.end_s = time.perf_counter()
        if self._tracer is not None:
            self._tracer._finished(self)

    # ---- derived ----
    @property
    def wall_s(self) -> float:
        end = self.end_s if self.end_s is not None else time.perf_counter()
        return end - self.start_s

    def phase_totals(self) -> Dict[str, float]:
        """Total seconds per phase name (a phase may recur, e.g. pad /
        execute once per chunk of an oversized batch).  Memoized once
        the span is finished — the serve path reads it twice per
        request (ring aggregation, then the fleet-gap computation) and
        the rebuild showed up against the traced-throughput gate.
        Treat the returned dict as read-only."""
        if self._totals is not None:
            return self._totals
        out: Dict[str, float] = {}
        for name, t0, t1 in self.phases:
            if t1 is None:
                continue
            out[name] = out.get(name, 0.0) + (t1 - t0)
        if self.end_s is not None:
            self._totals = out
        return out

    @property
    def phase_total_s(self) -> float:
        return sum(self.phase_totals().values())

    @property
    def coverage(self) -> float:
        """Fraction of the span wall time covered by phases — the
        acceptance gate for "no phase gaps" (phases are internally
        contiguous, so 1 - coverage is exactly the head + tail slack)."""
        wall = self.wall_s
        return (self.phase_total_s / wall) if wall > 0 else 1.0

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "trace_id": self.trace_id,
            "name": self.name,
            "labels": dict(self.labels),
            "start_unix_s": round(self.start_wall, 6),
            # the monotonic start too: paired with the recorder's
            # meta.json wall/mono anchor it places this span on the
            # pod timeline without trusting the wall clock per span
            "start_mono_s": round(self.start_s, 6),
            "wall_ms": round(self.wall_s * 1e3, 4),
            "phases": [{"name": n,
                        "start_ms": round((t0 - self.start_s) * 1e3, 4),
                        "dur_ms": (None if t1 is None
                                   else round((t1 - t0) * 1e3, 4))}
                       for n, t0, t1 in self.phases],
            "phase_total_ms": round(self.phase_total_s * 1e3, 4),
            "coverage": round(self.coverage, 4),
            "events": list(self.events),
        }
        if self.children:
            dec = _CHILD_DECODER
            kids = []
            for ch in self.children:
                if isinstance(ch, str):
                    ch = dec(ch) if dec is not None else None
                    if ch is None:
                        continue
                kids.append(ch)
            out["children"] = kids
        return out


class Tracer:
    """Span factory + bounded ring buffer of recent finished spans +
    per-phase duration aggregation.

    One tracer per serving process is the expected shape; the registry
    and the web frontend share it.  ``capacity`` bounds memory: the ring
    holds the most recent N finished spans, aggregates are O(#phases).

    Tail sampling (``tail_quantile``): the ring treats every span
    equally and washes the interesting ones out under load, so the
    tracer additionally RETAINS full span trees for exactly the
    requests worth a postmortem — every errored span, plus spans whose
    wall time clears the running ``tail_quantile`` of recent walls —
    in a store bounded by ``tail_cap`` (fastest non-errored exemplar
    evicted first).  ``exemplars()`` lists them and ``families()``
    publishes each as a ``zoo_trace_exemplar_ms`` sample whose
    ``trace_id`` label is the join key the tracefleet stitcher
    reconstructs a cross-process waterfall from.
    """

    #: recent-wall reservoir size and threshold refresh period for the
    #: tail sampler: sorting 256 floats every finish showed up against
    #: sub-ms requests, so the quantile threshold refreshes every 32
    #: finishes instead — exemplar selection is a sieve, not a ruling
    _TAIL_WINDOW = 256
    _TAIL_REFRESH = 32

    def __init__(self, capacity: int = 256,
                 tail_quantile: Optional[float] = None,
                 tail_cap: int = 64):
        self.capacity = int(capacity)
        self._ring: "deque[Span]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        # phase -> [count, total_s, max_s]
        self._agg: Dict[str, List[float]] = {}
        self._span_count = 0
        # tail-sampled exemplar store: trace_id -> retained Span
        self.tail_quantile = tail_quantile
        self.tail_cap = max(int(tail_cap), 1)
        self._tail: Dict[str, Span] = {}
        self._tail_walls: "deque[float]" = deque(maxlen=self._TAIL_WINDOW)
        self._tail_thr: Optional[float] = None

    def start_span(self, name: str = "request",
                   trace_id: Optional[str] = None,
                   **labels: Any) -> Span:
        return Span(self, name, trace_id=trace_id, labels=labels)

    @contextlib.contextmanager
    def request(self, name: str = "request",
                trace_id: Optional[str] = None, **labels: Any):
        """Start a span, activate it for the calling thread, finish it
        on exit — the one-liner for benches and tests.  Activation is
        inlined (no nested context manager): this wrapper sits inside
        the overhead the bench gate bounds."""
        global _ENABLED
        span = Span(self, name, trace_id=trace_id, labels=labels)
        token = _SPAN_VAR.set(span)
        if not _ENABLED:
            _ENABLED = True
        try:
            yield span
        finally:
            _SPAN_VAR.reset(token)
            span.finish()

    def _finished(self, span: Span):
        with self._lock:
            self._ring.append(span)
            self._span_count += 1
            for phase, dur in span.phase_totals().items():
                agg = self._agg.get(phase)
                if agg is None:
                    self._agg[phase] = [1, dur, dur]
                else:
                    agg[0] += 1
                    agg[1] += dur
                    agg[2] = max(agg[2], dur)
            if self.tail_quantile is not None:
                self._tail_sample(span)
        hook = _FINISH_HOOK  # outside the lock: the hook does file I/O
        if hook is not None:
            try:
                hook(span)
            except Exception:
                pass  # the flight recorder must never fail a request

    def _tail_sample(self, span: Span) -> None:
        """Retention decision for one finished span (caller holds the
        lock).  Errored spans always stay; otherwise the span's wall
        must clear the cached quantile threshold of recent walls."""
        wall = span.wall_s
        walls = self._tail_walls
        walls.append(wall)
        if self._tail_thr is None \
                or self._span_count % self._TAIL_REFRESH == 0:
            ws = sorted(walls)
            idx = min(int(len(ws) * self.tail_quantile), len(ws) - 1)
            self._tail_thr = ws[idx]
        if "error" not in span.labels and wall < self._tail_thr:
            return
        self._tail[span.trace_id] = span
        while len(self._tail) > self.tail_cap:
            victim = None
            fastest = None
            for tid, s in self._tail.items():
                if "error" in s.labels:
                    continue
                w = s.wall_s
                if fastest is None or w < fastest:
                    fastest, victim = w, tid
            if victim is None:
                # every exemplar errored: oldest insertion goes
                victim = next(iter(self._tail))
            del self._tail[victim]

    # ---- read side ----
    @property
    def span_count(self) -> int:
        with self._lock:
            return self._span_count

    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The most recent ``n`` finished spans (all when None),
        oldest first, as dicts.  ``n <= 0`` returns [] — slicing with
        ``-0`` would silently mean "everything", and this is reachable
        straight from ``GET /traces?n=``."""
        with self._lock:
            spans = list(self._ring)
        if n is not None:
            spans = spans[-n:] if n > 0 else []
        return [s.to_dict() for s in spans]

    def find_span(self, trace_id: str) -> "Optional[Span]":
        """The finished :class:`Span` object itself (ring newest-first,
        then the tail store) — the allocation-free lookup the worker's
        reply piggyback uses on the hot serve path; most callers want
        :meth:`find`, which returns the serialized dict."""
        with self._lock:
            for s in reversed(self._ring):
                if s.trace_id == trace_id:
                    return s
            return self._tail.get(trace_id)

    def find(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Ring first (newest wins), then the tail-exemplar store —
        an exemplar ``trace_id`` read off a scrape stays resolvable
        long after the ring washed the span out."""
        s = self.find_span(trace_id)
        return s.to_dict() if s is not None else None

    def retire(self, **labels: Any) -> int:
        """Drop finished spans whose labels match ALL of ``labels``
        (e.g. ``retire(model="ncf")`` when that model is undeployed):
        a long-lived process cycling many models must not keep dead
        models' spans pinned in the ring until traffic happens to wash
        them out.  Phase aggregates are label-free totals and stay.
        Returns the number of spans dropped."""
        if not labels:
            return 0
        with self._lock:
            kept = [s for s in self._ring
                    if any(s.labels.get(k) != v
                           for k, v in labels.items())]
            dropped = len(self._ring) - len(kept)
            if dropped:
                self._ring.clear()
                self._ring.extend(kept)
            # exemplars pin spans too — a retired model's must go
            # (not counted: the return value is ring spans dropped,
            # and a span can sit in both structures at once)
            for tid in [tid for tid, s in self._tail.items()
                        if all(s.labels.get(k) == v
                               for k, v in labels.items())]:
                del self._tail[tid]
        return dropped

    def phase_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-phase duration aggregation over every finished span."""
        with self._lock:
            return {phase: {"count": int(c),
                            "total_s": round(total, 6),
                            "mean_ms": round(total / c * 1e3, 4),
                            "max_ms": round(mx * 1e3, 4)}
                    for phase, (c, total, mx) in sorted(self._agg.items())}

    def exemplars(self) -> List[Dict[str, Any]]:
        """The tail-retained exemplar index: one row per retained span
        tree, newest-insertion last.  ``kind`` is ``error`` or
        ``slow``; the full tree is ``find(trace_id)``."""
        with self._lock:
            spans = list(self._tail.values())
        return [{"trace_id": s.trace_id,
                 "kind": "error" if "error" in s.labels else "slow",
                 "model": str(s.labels.get("model", "")),
                 "wall_ms": round(s.wall_s * 1e3, 4)}
                for s in spans]

    def families(self) -> List[Family]:
        """Prometheus collector (plug into MetricsRegistry)."""
        with self._lock:
            agg = {k: list(v) for k, v in self._agg.items()}
            count = self._span_count
            tail = list(self._tail.values())
        fams = [Family("counter", "zoo_trace_spans_total",
                       "finished request spans",
                       [({}, count)])]
        fams.append(Family(
            "counter", "zoo_trace_phase_seconds_total",
            "cumulative seconds spent per request phase",
            [({"phase": p}, v[1]) for p, v in sorted(agg.items())]))
        fams.append(Family(
            "counter", "zoo_trace_phase_count_total",
            "phase occurrences across finished spans",
            [({"phase": p}, v[0]) for p, v in sorted(agg.items())]))
        if tail:
            # the exemplar link: a scrape row whose trace_id label
            # names a span tree this process still holds in full —
            # cardinality is bounded by tail_cap, and the stitcher
            # (tracefleet.py) turns the id into a pod waterfall
            fams.append(Family(
                "gauge", "zoo_trace_exemplar_ms",
                "tail-sampled exemplar traces (slowest-quantile and "
                "errored requests): wall ms, joined on trace_id",
                [({"model": str(s.labels.get("model", "")),
                   "kind": ("error" if "error" in s.labels
                            else "slow"),
                   "trace_id": s.trace_id},
                  round(s.wall_s * 1e3, 4)) for s in tail]))
        return fams
