"""XLA profiling hooks: compiles, transfers, and live buffers as
metrics + span events instead of sanitizer aborts.

zoolint's ``sanitize()`` turns an unexpected compile or implicit
transfer into a hard failure — right for CI, wrong for production,
where the question is "how often and where".  This module subscribes
the SAME jax monitoring stream (``backend_compile`` duration events
fire exactly once per real XLA compile; cache hits fire nothing, so
counts are exact) but records instead of raising:

* every compile increments ``zoo_xla_compiles_total`` / adds to
  ``zoo_xla_compile_seconds_total`` AND lands as a ``backend_compile``
  event on the current request span (when one is active via
  ``trace.activate`` — e.g. an unwarmed shape compiling on the request
  path shows up IN that request's trace);
* other jax duration events count under
  ``zoo_xla_events_total{event=...}`` (bounded cardinality: jax's own
  event vocabulary);
* the serving dispatch path reports its explicit uploads through
  :func:`note_transfer` (``zoo_transfers_total{direction=...}``) — one
  flag-check when no hooks are installed;
* ``zoo_live_buffers`` is a scrape-time gauge over
  ``jax.live_arrays()`` — a leak shows as monotonic growth.

Install once per process (the web service does), plug
``handle.families`` into a :class:`~.metrics.MetricsRegistry`::

    handle = profile.install()
    registry.register_collector(handle.families)
    ...
    handle.close()
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from . import trace
from .metrics import Family

_COMPILE_EVENT_SUBSTR = "backend_compile"

_lock = threading.Lock()
_installed: "Optional[XlaProfile]" = None


class XlaProfile:
    """Counters fed by jax's monitoring stream (see module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_seconds = 0.0
        self._events: Dict[str, int] = {}
        self._transfers: Dict[str, int] = {}
        self._closed = False

    # ---- feed side ----
    def _on_duration_event(self, key: str, duration: float, **kw):
        if self._closed:
            return
        if _COMPILE_EVENT_SUBSTR in key:
            with self._lock:
                self.compiles += 1
                self.compile_seconds += duration
            span = trace.current_span()
            if span is not None:
                span.event("backend_compile",
                           seconds=round(duration, 6), key=key)
        else:
            with self._lock:
                self._events[key] = self._events.get(key, 0) + 1

    def _note_transfer(self, direction: str):
        with self._lock:
            self._transfers[direction] = \
                self._transfers.get(direction, 0) + 1

    # ---- read side ----
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"compiles": self.compiles,
                    "compile_seconds": round(self.compile_seconds, 6),
                    "events": dict(self._events),
                    "transfers": dict(self._transfers)}

    def families(self) -> List[Family]:
        """Prometheus collector (plug into MetricsRegistry)."""
        with self._lock:
            compiles = self.compiles
            seconds = self.compile_seconds
            events = dict(self._events)
            transfers = dict(self._transfers)
        fams = [
            Family("counter", "zoo_xla_compiles_total",
                   "XLA backend compiles observed since install",
                   [({}, compiles)]),
            Family("counter", "zoo_xla_compile_seconds_total",
                   "cumulative XLA compile wall seconds",
                   [({}, seconds)]),
        ]
        if events:
            fams.append(Family(
                "counter", "zoo_xla_events_total",
                "other jax monitoring duration events, by key",
                [({"event": k}, v) for k, v in sorted(events.items())]))
        if transfers:
            fams.append(Family(
                "counter", "zoo_transfers_total",
                "explicit host<->device transfers on the serving "
                "dispatch path, by direction",
                [({"direction": d}, v)
                 for d, v in sorted(transfers.items())]))
        fams.append(Family(
            "gauge", "zoo_live_buffers",
            "live jax device buffers (scrape-time)",
            [({}, _live_buffer_count())]))
        return fams

    def close(self):
        """Unhook from jax monitoring (idempotent)."""
        global _installed
        self._closed = True
        with _lock:
            if _installed is self:
                _installed = None
        try:
            from jax._src import monitoring as _monitoring
            unhook = getattr(
                _monitoring,
                "_unregister_event_duration_listener_by_callback", None)
            if unhook is not None:
                unhook(self._on_duration_event)
        except Exception:
            pass  # _closed already made the listener inert


def _live_buffer_count() -> float:
    try:
        import jax
        return float(len(jax.live_arrays()))
    except Exception:
        return float("nan")


def install() -> XlaProfile:
    """Subscribe an :class:`XlaProfile` to jax's monitoring stream and
    make it the process target for :func:`note_transfer`.  Returns the
    existing handle when one is already installed (one stream, one
    consumer)."""
    global _installed
    with _lock:
        if _installed is not None:
            return _installed
        handle = XlaProfile()
        from jax._src import monitoring as _monitoring
        _monitoring.register_event_duration_secs_listener(
            handle._on_duration_event)
        _installed = handle
        return handle


def installed() -> "Optional[XlaProfile]":
    return _installed


def note_transfer(direction: str = "h2d"):
    """Count one explicit transfer (called by the serving dispatch
    path around its ``device_put``).  A single flag-check when no
    profile is installed."""
    handle = _installed
    if handle is not None:
        handle._note_transfer(direction)
