"""Pod-level telemetry aggregation: N per-rank Prometheus snapshots in,
ONE pod scrape out.

The cross-process half of the metrics plane: every worker's flight
recorder publishes atomic ``metrics.prom`` snapshots under the shared
``ZOO_FLIGHTREC_DIR`` (flightrec.py layout, ``rank{r}.i{i}/``); this
module merges them into a single exposition a Prometheus server — or
ROADMAP item 2's serving router — can scrape from one place:

* every sample gains a ``rank`` label (a pre-existing ``rank`` label on
  a sample is preserved — the snapshot's own labeling wins);
* the same family name across snapshots merges into one ``# TYPE``
  block, with a type conflict raising rather than shipping an invalid
  exposition (the render-side rule, enforced here at parse time too);
* the same SERIES across a rank's incarnations follows metric
  semantics: **counters sum** (each restarted process restarts from 0,
  so the rank's true total is the sum over its incarnations) while
  **gauges last-write-win** (newest incarnation's value is the live
  one);
* counters additionally emit a **pod-total series** without the
  ``rank`` label — per-rank step counters sum to the pod total in the
  same scrape, which is the faulttrain drill's aggregation gate.

Summaries ride through per-rank (quantiles cannot be summed); their
``_sum``/``_count`` samples stay attached to the base family so the
output re-parses cleanly.

Also a CLI — the supervisor runs the same code in-process when it
writes ``pod_metrics.prom`` next to a postmortem::

    python -m analytics_zoo_tpu.observability.aggregate DIR          # scrape
    python -m analytics_zoo_tpu.observability.aggregate DIR --view   # stragglers
    python -m analytics_zoo_tpu.observability.aggregate DIR --watch 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .metrics import (Family, parse_prometheus_text, render_prometheus)

#: the per-rank step counter (train/metrics.py) the straggler view and
#: the drill's sum-to-pod-total gate key on
STEP_FAMILY = "zoo_train_steps_total"

_SUMMABLE = ("counter",)


def iter_snapshots(base_dir: str
                   ) -> List[Tuple[int, int, str]]:
    """``(rank, incarnation, path)`` for every snapshot under
    ``base_dir``: the flightrec layout (``rank{r}.i{i}/metrics.prom``)
    plus flat ``rank{r}.prom`` files workers may drop directly.
    Sorted by (rank, incarnation) so incarnation order — which the
    gauge last-write rule depends on — is deterministic."""
    out: List[Tuple[int, int, str]] = []
    try:
        names = os.listdir(base_dir)
    except OSError:
        return out
    for name in names:
        full = os.path.join(base_dir, name)
        if os.path.isdir(full) and name.startswith("rank") \
                and ".i" in name:
            try:
                rank_s, inc_s = name[4:].split(".i", 1)
                rank, inc = int(rank_s), int(inc_s)
            except ValueError:
                continue
            prom = os.path.join(full, "metrics.prom")
            if os.path.exists(prom):
                out.append((rank, inc, prom))
        elif name.startswith("rank") and name.endswith(".prom"):
            try:
                rank = int(name[4:-5])
            except ValueError:
                continue
            out.append((rank, 0, full))
    out.sort()
    return out


def rank_labeled(fams: Iterable[Family], rank: Any) -> List[Family]:
    """Stamp ``rank`` on every sample missing one — how a non-worker
    process (the fleet router scraping its own tracer) joins a pod
    exposition without colliding with either the per-rank worker
    series or the rank-less counter pod totals this module emits.
    A sample that already carries a rank keeps it (the snapshot's own
    labeling wins, same rule as :func:`merge_snapshots`)."""
    out: List[Family] = []
    for fam in fams:
        samples: List[Tuple] = []
        for s in fam.samples:
            labels = dict(s[0])
            labels.setdefault("rank", str(rank))
            samples.append((labels, *s[1:]))
        out.append(Family(fam.mtype, fam.name, fam.help, samples))
    return out


def _base_family(name: str, types: Dict[str, str]) -> Tuple[str, str]:
    """Resolve a sample name to its (family name, type): summary
    ``_sum``/``_count`` samples belong to their base family."""
    mtype = types.get(name)
    if mtype is not None:
        return name, mtype
    for suffix in ("_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            btype = types.get(base)
            if btype in ("summary", "histogram"):
                return base, btype
    return name, "untyped"


def merge_snapshots(parsed: Iterable[Tuple[int, Dict[str, Any]]]
                    ) -> List[Family]:
    """Merge already-parsed per-rank scrapes (``(rank, parsed)`` pairs
    in incarnation order) into one family list (module docstring for
    the merge semantics).  The aggregator hot loop — zoolint covers
    it."""
    # family -> {"mtype", "help", series: {(sample_name, labelkey): value}}
    fams: Dict[str, Dict[str, Any]] = {}
    totals: Dict[Tuple[str, str, Tuple], float] = {}
    for rank, p in parsed:
        types: Dict[str, str] = p.get("types", {})
        helps: Dict[str, str] = p.get("helps", {})
        for (name, labelkey), value in p.get("samples", {}).items():
            fam_name, mtype = _base_family(name, types)
            fam = fams.get(fam_name)
            if fam is None:
                fam = fams.setdefault(fam_name, {
                    "mtype": mtype, "help": helps.get(fam_name, ""),
                    "series": {}})
            elif fam["mtype"] != mtype and mtype != "untyped":
                if fam["mtype"] == "untyped":
                    fam["mtype"] = mtype
                else:
                    raise ValueError(
                        f"family {fam_name!r} collected as both "
                        f"{fam['mtype']} and {mtype} across snapshots")
            labels = dict(labelkey)
            labels.setdefault("rank", str(rank))
            key = (name, tuple(sorted(labels.items())))
            series = fam["series"]
            # sum/total decisions use the RESOLVED family type: a
            # snapshot that lost its # TYPE line (hand-dropped flat
            # files) must not demote an established counter to
            # last-write and fall out of the pod total
            resolved = fam["mtype"]
            if resolved in _SUMMABLE and key in series:
                series[key] += value  # counter across incarnations
            else:
                series[key] = value  # gauge/summary: last write wins
            if resolved in _SUMMABLE and "rank" not in dict(labelkey):
                # pod total, keyed by the rank-LESS label set (a sample
                # that already carried its own rank label has no
                # meaningful pod rollup)
                tkey = (fam_name, name, labelkey)
                totals[tkey] = totals.get(tkey, 0.0) + value
    out: List[Family] = []
    for fam_name in sorted(fams):
        fam = fams[fam_name]
        # histograms ride through untyped (Family has no histogram
        # mtype and nothing here emits one)
        mtype = (fam["mtype"] if fam["mtype"] in
                 ("counter", "gauge", "summary") else "untyped")
        samples: List[Tuple] = []
        for (name, labelkey), value in sorted(fam["series"].items()):
            samples.append((dict(labelkey), value, name))
        for (tfam, name, labelkey), value in sorted(totals.items()):
            if tfam == fam_name:
                # the pod total: the same family WITHOUT a rank label
                samples.append((dict(labelkey), value, name))
        out.append(Family(mtype, fam_name, fam["help"], samples))
    return out


def aggregate_files(entries: Iterable[Tuple[int, int, str]]
                    ) -> List[Family]:
    parsed: List[Tuple[int, Dict[str, Any]]] = []
    for rank, _inc, path in entries:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue  # a snapshot mid-replace or a reaped worker's dir
        parsed.append((rank, parse_prometheus_text(text)))
    return merge_snapshots(parsed)


def aggregate_dir(base_dir: str) -> str:
    """The pod scrape: every snapshot under ``base_dir`` merged and
    rendered (empty exposition when there are none yet)."""
    fams = aggregate_files(iter_snapshots(base_dir))
    return render_prometheus(fams) if fams else "\n"


# ------------------------------------------------------- straggler view
def step_counts(base_dir: str) -> Dict[int, float]:
    """Per-rank completed-step totals (summed over incarnations) from
    the snapshots' ``zoo_train_steps_total``."""
    out: Dict[int, float] = {}
    for fam in aggregate_files(iter_snapshots(base_dir)):
        if fam.name != STEP_FAMILY:
            continue
        for s in fam.samples:
            labels = s[0]
            if "rank" in labels:
                try:
                    out[int(labels["rank"])] = float(s[1])
                except (TypeError, ValueError):
                    continue
    return out


def step_view(base_dir: str,
              prev: Optional[Dict[int, float]] = None,
              interval_s: Optional[float] = None) -> Dict[str, Any]:
    """A live step-rate / straggler summary: per-rank steps, step rate
    since the previous observation (when one is given), and each
    rank's lag behind the most advanced rank — the metrics plane's
    answer to "which worker is holding the pod back"."""
    counts = step_counts(base_dir)
    lead = max(counts.values()) if counts else 0.0
    ranks = {}
    for rank, steps in sorted(counts.items()):
        row: Dict[str, Any] = {"steps": steps, "lag": lead - steps}
        if prev is not None and interval_s and rank in prev:
            row["steps_per_s"] = round(
                max(steps - prev[rank], 0.0) / interval_s, 3)
        ranks[rank] = row
    stragglers = [r for r, row in ranks.items() if row["lag"] > 0]
    return {"ranks": ranks, "lead_steps": lead,
            "stragglers": stragglers, "counts": counts}


def _print_view(view: Dict[str, Any]) -> None:
    ranks = view["ranks"]
    if not ranks:
        print("(no snapshots yet)")
        return
    for rank, row in sorted(ranks.items()):
        rate = row.get("steps_per_s")
        print(f"rank {rank}: steps={row['steps']:.0f} "
              f"lag={row['lag']:.0f}"
              + (f" rate={rate}/s" if rate is not None else ""))
    if view["stragglers"]:
        print(f"stragglers: {view['stragglers']}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.observability.aggregate",
        description="Merge per-rank Prometheus snapshots into one "
                    "pod-level scrape (flight-recorder layout)")
    ap.add_argument("dir", help="shared snapshot dir (ZOO_FLIGHTREC_DIR)")
    ap.add_argument("--out", default=None,
                    help="write the scrape here (atomically) instead "
                         "of stdout")
    ap.add_argument("--view", action="store_true",
                    help="print the per-rank step/straggler view "
                         "instead of the scrape")
    ap.add_argument("--watch", type=float, default=None, metavar="SEC",
                    help="repeat the step view every SEC seconds with "
                         "live step rates (Ctrl-C to stop)")
    ap.add_argument("--json", action="store_true",
                    help="emit the view as JSON (with --view)")
    args = ap.parse_args(argv)

    if args.watch:
        prev: Optional[Dict[int, float]] = None
        try:
            while True:
                view = step_view(args.dir, prev, args.watch)
                print(f"--- {time.strftime('%H:%M:%S')} ---")
                _print_view(view)
                prev = view["counts"]
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0

    if args.view:
        view = step_view(args.dir)
        if args.json:
            view.pop("counts", None)
            print(json.dumps(view, indent=2))
        else:
            _print_view(view)
        return 0

    text = aggregate_dir(args.dir)
    if args.out:
        from .flightrec import atomic_write
        atomic_write(args.out, text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
