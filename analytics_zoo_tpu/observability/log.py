"""Structured logging with request correlation — the sanctioned way to
log from serving-hot code (zoolint ZL601 flags bare ``print`` / stdlib
``logging`` calls there).

Why not plain ``logging``: a free-text line from the middle of the
dispatch path cannot be joined back to the request that produced it.
Records here are single-line JSON with a stable field set —
``ts``/``level``/``logger``/``msg`` plus caller fields — and the
current request's ``request_id`` (span trace id) attached
automatically, so one ``grep request_id`` yields the request's full
story across threads.  Under the supervisor env contract
(``ZOO_TPU_PROCESS_ID`` / ``ZOO_RESTART_COUNT``) every record also
auto-stamps ``rank`` and ``incarnation``, so a pod's merged log
stream stays attributable per worker after aggregation; records
additionally feed the flight recorder's tail when one is configured
(``observability/flightrec.py``).

Delivery still goes through the stdlib root machinery (one
``logging.Logger`` per name underneath), so existing handler/level
configuration keeps working::

    from analytics_zoo_tpu.observability.log import get_logger
    slog = get_logger("zoo.serving")
    slog.info("dispatch", bucket=8, rows=5)
    # {"ts": ..., "level": "info", "logger": "zoo.serving",
    #  "msg": "dispatch", "request_id": "4f0c...", "bucket": 8, "rows": 5}
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

from . import trace
from .. import envcontract

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR,
           "critical": logging.CRITICAL}

# process identity stamped onto every record when the PR 10 supervisor
# env contract is present (ZOO_TPU_PROCESS_ID / JAX_PROCESS_ID rank,
# ZOO_RESTART_COUNT incarnation) — a pod's merged log stream is
# attributable per worker after aggregation.  Cached; faults.refresh()
# re-reads it at Trainer.fit entry.
_identity: "Optional[Dict[str, int]]" = None

# flight-recorder tail sink (flightrec.configure): sees every record,
# including levels the stdlib handler config would drop — the black
# box wants the full tail, the console keeps its own thresholds.
_TAIL_HOOK = None


def refresh_identity() -> None:
    """Re-read the rank/incarnation env contract (called by
    ``train.faults.refresh`` so a supervisor-provided environment takes
    effect without import-order coupling)."""
    global _identity
    rank = (envcontract.env_str("ZOO_TPU_PROCESS_ID")
            or os.environ.get("JAX_PROCESS_ID"))
    incarnation = envcontract.env_str("ZOO_RESTART_COUNT")
    ident: Dict[str, int] = {}
    # tolerate empty/garbage values (a stale `export ZOO_RESTART_COUNT=`
    # must degrade to no stamp, never crash every log call)
    try:
        if rank:
            ident["rank"] = int(rank)
        if incarnation:
            ident["incarnation"] = int(incarnation)
    except ValueError:
        pass
    _identity = ident


def set_tail_hook(fn) -> None:
    global _TAIL_HOOK
    _TAIL_HOOK = fn


class StructuredLogger:
    """JSON-lines logger bound to one name; see module docstring."""

    __slots__ = ("name", "_logger")

    def __init__(self, name: str):
        self.name = name
        self._logger = logging.getLogger(name)

    def _emit(self, level: str, msg: str, fields: Dict[str, Any]):
        lvl = _LEVELS[level]
        tail = _TAIL_HOOK
        enabled = self._logger.isEnabledFor(lvl)
        if not enabled and tail is None:
            return
        global _identity
        if _identity is None:
            refresh_identity()
        record: Dict[str, Any] = {
            "ts": round(time.time(), 6), "level": level,
            "logger": self.name, "msg": msg}
        record.update(_identity)
        span = trace.current_span()
        if span is not None:
            record["request_id"] = span.trace_id
        record.update(fields)
        if tail is not None:
            try:
                tail(record)
            except Exception:
                pass  # the black box must never fail the caller
        if enabled:
            self._logger.log(lvl, "%s",
                             json.dumps(record, default=str,
                                        separators=(",", ":")))

    def debug(self, msg: str, **fields: Any):
        self._emit("debug", msg, fields)

    def info(self, msg: str, **fields: Any):
        self._emit("info", msg, fields)

    def warning(self, msg: str, **fields: Any):
        self._emit("warning", msg, fields)

    def error(self, msg: str, **fields: Any):
        self._emit("error", msg, fields)

    def critical(self, msg: str, **fields: Any):
        self._emit("critical", msg, fields)


_loggers: Dict[str, StructuredLogger] = {}


def get_logger(name: Optional[str] = None) -> StructuredLogger:
    """The structured logger for ``name`` (cached per name)."""
    key = name or "analytics_zoo_tpu"
    slog = _loggers.get(key)
    if slog is None:
        slog = _loggers.setdefault(key, StructuredLogger(key))
    return slog
