"""Crash-safe per-process flight recorder — the black box a supervisor
harvests after reaping a worker.

PR 10 made pod death survivable; this module makes it *explainable*.
Everything the single-process observability stack knows (spans, metric
families, structured-log lines, per-step heartbeats) dies with the
process on SIGKILL — exactly the moment it is most needed.  The flight
recorder continuously lands a bounded tail of that state on disk under
a per-incarnation directory, with write disciplines chosen so a kill at
ANY byte offset never yields a torn or ambiguous record:

* **event segments** (``events.seg`` + one rotated predecessor) hold
  length-prefix + CRC32 framed JSON records appended in a single
  ``os.write`` — a reader stops at the first short or checksum-failing
  record, so the worst a mid-write SIGKILL costs is the record in
  flight (the checkpoint commit lesson applied to telemetry);
* **metric snapshots** (``metrics.prom``) are full Prometheus-text
  renders of the registered collectors, throttled and published
  tmp+atomic-rename (the commit-manifest discipline) — the file is
  always a complete, parseable scrape;
* **meta.json** (pid / rank / incarnation / versions / start time) is
  written once at open, same tmp+rename.

Record types: ``span`` (a finished :class:`~.trace.Span` as dict),
``log`` (a structured-log record), ``hb`` (per-training-step liveness:
``{ts, step}`` — the postmortem's "last completed step" and heartbeat
timeline come from these).

Layout under the shared base directory (one per pod, the supervisor
points every worker at it via ``ZOO_FLIGHTREC_DIR``)::

    <base>/rank0.i0/{meta.json, events.seg[.old], metrics.prom}
    <base>/rank1.i0/...
    <base>/rank1.i1/...          # incarnation 1 after a restart

The read side (:func:`harvest`, :func:`write_postmortem`) is pure
stdlib and never throws on torn/absent data — a postmortem of a pod
that never got as far as recording anything still names the failed
rank from supervisor-side evidence.

Cost model: one ``None`` check per hooked call site when no recorder
is configured; ~a ``json.dumps`` + one buffered-fd ``os.write`` per
record when one is (the faulttrain overhead gate bounds this at
>= 0.95x the unrecorded step rate).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from . import log as log_mod
from . import trace as trace_mod
from .metrics import Family, process_info_family, render_prometheus
from .. import envcontract

#: shared pod directory; the supervising launcher exports this to every
#: worker (a pre-set value wins, so drills can harvest it themselves)
ENV_DIR = "ZOO_FLIGHTREC_DIR"

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
_SEGMENT = "events.seg"
_SEGMENT_OLD = "events.seg.old"
_METRICS = "metrics.prom"
_META = "meta.json"

_lock = threading.Lock()
_recorder: "Optional[FlightRecorder]" = None


def _env_int(*names: str) -> int:
    """First present env var as int; garbage ("", "stale") degrades to
    0 — telemetry identity must never crash a training job (same
    contract as log.refresh_identity)."""
    for name in names:
        # ZOO_* names route through the declared contract; the JAX_*
        # fallbacks are foreign and stay raw environ reads
        value = (envcontract.env_str(name) if name in envcontract.VARS
                 else os.environ.get(name))
        if value:
            try:
                return int(value)
            except ValueError:
                return 0
    return 0


def _env_rank() -> int:
    return _env_int("ZOO_TPU_PROCESS_ID", "JAX_PROCESS_ID")


def _env_incarnation() -> int:
    return _env_int("ZOO_RESTART_COUNT")


def atomic_write(path: str, data: str) -> None:
    """tmp + fsync + atomic-rename: the file at ``path`` is always a
    complete previous or complete new version (shared by the recorder,
    the supervisor's postmortem artifacts, and the aggregator CLI)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_atomic_write = atomic_write


class FlightRecorder:
    """One process's black box (module docstring).  Thread-safe: spans
    finish on dispatcher threads, logs come from anywhere, heartbeats
    from the training loop."""

    def __init__(self, base_dir: str, rank: Optional[int] = None,
                 incarnation: Optional[int] = None,
                 max_segment_bytes: int = 256 * 1024,
                 snapshot_interval_s: float = 2.0):
        self.rank = _env_rank() if rank is None else int(rank)
        self.incarnation = (_env_incarnation() if incarnation is None
                            else int(incarnation))
        self.dir = os.path.join(
            base_dir, f"rank{self.rank}.i{self.incarnation}")
        os.makedirs(self.dir, exist_ok=True)
        self.max_segment_bytes = int(max_segment_bytes)
        self.snapshot_interval_s = float(snapshot_interval_s)
        # RLock: _rotate_locked re-enters lexically (ZL401 discipline)
        self._wlock = threading.RLock()
        self._seg_path = os.path.join(self.dir, _SEGMENT)
        # O_APPEND: every record lands in one write() at the tail even
        # if some other handle (a forked child) still points here
        self._fd = os.open(self._seg_path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            self._seg_bytes = os.fstat(self._fd).st_size
        except OSError:
            self._seg_bytes = 0
        # keyed by function identity (module+qualname): registering a
        # REPLACEMENT source — e.g. a fresh StepProfiler's bound
        # families — supersedes the old instance instead of
        # double-publishing the same series from a stale one
        self._collectors: Dict[Tuple[str, str],
                               Callable[[], Iterable[Family]]] = {
            ("flightrec", "process_info"):
                lambda: [process_info_family()]}
        self._snap_last = 0.0
        self._closed = False
        self._write_meta()

    # ------------------------------------------------------- write side
    def _write_meta(self) -> None:
        start_unix = time.time()
        meta: Dict[str, Any] = {
            "pid": os.getpid(), "rank": self.rank,
            "incarnation": self.incarnation,
            "start_unix": round(start_unix, 6),
            # wall/monotonic anchor, sampled back-to-back: spans
            # record perf_counter starts, so anchor.unix +
            # (span.start_mono_s - anchor.mono) places any of this
            # incarnation's spans on the wall clock with ONE
            # correction per rank — the trace stitcher's clock
            # alignment (observability/tracefleet.py)
            "anchor": {"unix": round(start_unix, 6),
                       "mono": round(time.perf_counter(), 6)}}
        try:
            import jax
            import jaxlib
            meta["jax"] = jax.__version__
            meta["jaxlib"] = jaxlib.__version__
        except Exception:
            pass  # recorder must work in jax-free processes too
        try:
            _atomic_write(os.path.join(self.dir, _META),
                          json.dumps(meta, default=str))
        except OSError:
            pass  # telemetry is best-effort; never fail the worker

    def _append(self, record: Dict[str, Any]) -> None:
        """Append one framed record (the hot path — zoolint covers it).
        A SIGKILL between the write and the disk is the reader's
        problem by design: the frame's length+CRC makes a torn tail
        detectable, never silently wrong."""
        self.record_batch((record,))

    def _rotate_locked(self) -> None:
        """Bound the on-disk tail to two segments (caller already
        holds the write lock — re-entered lexically).  The rename is
        atomic; a crash between steps loses at most the older
        segment.  A failed REOPEN kills the recorder rather than
        leave ``_fd`` naming a closed descriptor — a later write to a
        recycled fd number would corrupt whatever file reused it."""
        with self._wlock:
            os.close(self._fd)
            self._fd = -1
            try:
                os.replace(self._seg_path,
                           os.path.join(self.dir, _SEGMENT_OLD))
            except OSError:
                pass
            try:
                self._fd = os.open(
                    self._seg_path,
                    os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                # replace may have failed above: size from the fd, not
                # an assumed-fresh 0, keeps the rotation bound honest
                self._seg_bytes = os.fstat(self._fd).st_size
            except OSError:
                self._closed = True

    def record_span(self, span_dict: Dict[str, Any]) -> None:
        self._append({"t": "span", "ts": round(time.time(), 6),
                      "span": span_dict})

    def record_log(self, record: Dict[str, Any]) -> None:
        # type tag LAST: a caller log field named "t" must lose to the
        # tag, not silently reclassify the record out of the log tail
        self._append({**record, "t": "log"})

    def record_step(self, step: int) -> None:
        """Per-training-step liveness marker: the postmortem's "last
        completed step" is the last one of these on disk."""
        self._append({"t": "hb", "ts": round(time.time(), 6),
                      "step": int(step)})

    def record(self, rtype: str, **fields: Any) -> None:
        """A generic typed record (e.g. the step profiler's compact
        per-step phase entry, ``t="step"``)."""
        self._append({"t": rtype, "ts": round(time.time(), 6),
                      **fields})

    def record_batch(self, records: Sequence[Dict[str, Any]]) -> None:
        """Append many records in ONE write: each record is framed
        individually (the reader sees no difference) but the syscall
        is amortized — the step profiler batches its per-step phase
        entries this way so the training loop's write-through cost
        stays with the tiny liveness marker alone."""
        if self._closed or not records:
            return
        frames = []
        for record in records:
            payload = json.dumps(record, default=str,
                                 separators=(",", ":")).encode("utf-8")
            frames.append(_HEADER.pack(
                len(payload), zlib.crc32(payload) & 0xffffffff) + payload)
        blob = b"".join(frames)
        with self._wlock:
            if self._closed:
                return
            try:
                os.write(self._fd, blob)
                self._seg_bytes += len(blob)
                if self._seg_bytes >= self.max_segment_bytes:
                    self._rotate_locked()
            except OSError:
                pass

    # ---------------------------------------------------- metric snaps
    def add_collector(self, fn: Callable[[], Iterable[Family]]) -> None:
        """Register a family source included in every snapshot.
        Keyed by the function's module+qualname, so re-registering is
        idempotent AND a new instance's bound method replaces its
        predecessor's."""
        key = (getattr(fn, "__module__", "") or "",
               getattr(fn, "__qualname__", "") or repr(fn))
        with self._wlock:
            self._collectors[key] = fn

    def snapshot_metrics(self, force: bool = False) -> bool:
        """Render the registered collectors to ``metrics.prom``
        (tmp+atomic-rename), throttled to ``snapshot_interval_s``
        unless forced.  Returns True when a snapshot was written."""
        now = time.monotonic()
        if not force and now - self._snap_last < self.snapshot_interval_s:
            return False
        self._snap_last = now
        with self._wlock:
            collectors = list(self._collectors.values())
        fams: List[Family] = []
        for fn in collectors:
            try:
                fams.extend(fn())
            except Exception:
                continue  # one broken source must not drop the scrape
        try:
            _atomic_write(os.path.join(self.dir, _METRICS),
                          render_prometheus(fams))
        except (OSError, ValueError):
            return False
        return True

    # -------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Final snapshot + release the segment fd (idempotent)."""
        if self._closed:
            return
        self.snapshot_metrics(force=True)
        with self._wlock:
            if self._closed:
                return
            self._closed = True
            try:
                os.close(self._fd)
            except OSError:
                pass


# ------------------------------------------------------------ process
def configure(base_dir: str, **kwargs: Any) -> FlightRecorder:
    """Open THE process recorder and hook it into the tracer finish
    path and the structured logger's tail.  Idempotent: an existing
    recorder is returned unchanged (one black box per process)."""
    global _recorder
    with _lock:
        if _recorder is not None:
            return _recorder
        rec = FlightRecorder(base_dir, **kwargs)
        _recorder = rec
    trace_mod.set_finish_hook(lambda span: rec.record_span(span.to_dict()))
    log_mod.set_tail_hook(rec.record_log)
    return rec


def current() -> "Optional[FlightRecorder]":
    return _recorder


def install_from_env() -> "Optional[FlightRecorder]":
    """Open the process recorder when ``ZOO_FLIGHTREC_DIR`` is set (the
    supervising launcher's contract); None (and zero cost later) when
    it is not."""
    if _recorder is not None:
        return _recorder
    base = envcontract.env_str(ENV_DIR)
    if not base:
        return None
    try:
        return configure(base)
    except OSError:
        return None  # unwritable dir: run without a black box


def shutdown() -> None:
    """Final snapshot, close the segment, unhook the trace/log sinks,
    and clear the process recorder (idempotent).  ``configure`` /
    ``install_from_env`` may open a fresh one afterwards."""
    global _recorder
    with _lock:
        rec, _recorder = _recorder, None
    trace_mod.set_finish_hook(None)
    log_mod.set_tail_hook(None)
    if rec is not None:
        rec.close()


_reset_for_tests = shutdown  # test-isolation alias


# ----------------------------------------------------------- read side
def read_records(path: str) -> List[Dict[str, Any]]:
    """Decode one segment file, stopping cleanly at the first torn
    record (short frame, CRC mismatch, or undecodable payload)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return out
    off, n = 0, len(data)
    while off + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, off)
        start = off + _HEADER.size
        end = start + length
        if end > n:
            break  # torn tail: the record in flight at the kill
        payload = data[start:end]
        if zlib.crc32(payload) & 0xffffffff != crc:
            break
        try:
            out.append(json.loads(payload.decode("utf-8")))
        except (ValueError, UnicodeDecodeError):
            break
        off = end
    return out


def _read_dir(d: str, tail: int) -> Dict[str, Any]:
    records = (read_records(os.path.join(d, _SEGMENT_OLD))
               + read_records(os.path.join(d, _SEGMENT)))
    # "step" records (the profiler's compact per-step entries) carry a
    # step field too — both kinds feed the liveness timeline.  Batched
    # step records land AFTER later write-through hb records, so
    # restore chronology and collapse the hb/step duplicate a profiled
    # step produces (dict keyed by step keeps the last occurrence)
    hbs = sorted((r for r in records if r.get("t") in ("hb", "step")),
                 key=lambda r: (r.get("ts") or 0.0))
    hbs = list({r.get("step"): r for r in hbs}.values())
    spans = [r.get("span") for r in records if r.get("t") == "span"]
    steps = [{k: v for k, v in r.items() if k != "t"}
             for r in records if r.get("t") == "step"]
    logs = [{k: v for k, v in r.items() if k != "t"}
            for r in records if r.get("t") == "log"]
    meta: Dict[str, Any] = {}
    try:
        with open(os.path.join(d, _META)) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        pass
    metrics_path = os.path.join(d, _METRICS)
    out = {
        "meta": meta,
        "last_step": (int(hbs[-1]["step"]) if hbs else None),
        "heartbeats": [{"ts": h.get("ts"), "step": h.get("step")}
                       for h in hbs[-tail:]],
        "spans": spans[-tail:],
        "steps": steps[-tail:],
        "logs": logs[-tail:],
        "metrics_path": (metrics_path if os.path.exists(metrics_path)
                         else None),
    }
    return out


def harvest(base_dir: str, tail: int = 32) -> Dict[int, Dict[str, Any]]:
    """Read every rank's NEWEST incarnation directory under
    ``base_dir``.  Returns ``{rank: {meta, last_step, heartbeats,
    spans, logs, metrics_path, incarnations}}``; missing/torn data
    degrades to absent fields, never an exception."""
    found: Dict[int, List[int]] = {}
    try:
        names = os.listdir(base_dir)
    except OSError:
        return {}
    for name in names:
        if not name.startswith("rank") or ".i" not in name:
            continue
        try:
            rank_s, inc_s = name[4:].split(".i", 1)
            rank, inc = int(rank_s), int(inc_s)
        except ValueError:
            continue
        found.setdefault(rank, []).append(inc)
    out: Dict[int, Dict[str, Any]] = {}
    for rank, incs in sorted(found.items()):
        inc = max(incs)
        d = os.path.join(base_dir, f"rank{rank}.i{inc}")
        rec = _read_dir(d, tail)
        rec["incarnation"] = inc
        rec["incarnations"] = sorted(incs)
        out[rank] = rec
    return out


def write_postmortem(base_dir: str, out_path: str, *,
                     reason: str, failed_rank: Optional[int],
                     incarnation: int,
                     supervisor: Optional[Dict[int, Dict[str, Any]]] = None,
                     extra: Optional[Dict[str, Any]] = None,
                     tail: int = 32) -> Dict[str, Any]:
    """Harvest every worker's recorder and land ``pod_postmortem.json``
    (tmp+atomic-rename).  ``supervisor`` carries per-rank evidence only
    the supervisor has (exit rc, heartbeat-file age at reap) and is
    merged under each rank — so "why did rank 1 die" is answerable
    even when rank 1 never wrote a single record."""
    ranks: Dict[str, Dict[str, Any]] = {
        str(r): rec for r, rec in harvest(base_dir, tail=tail).items()}
    for r, sup in (supervisor or {}).items():
        ranks.setdefault(str(r), {}).update(sup)
    pm = {
        "reason": reason,
        "failed_rank": failed_rank,
        "incarnation": incarnation,
        "written_unix": round(time.time(), 6),
        **(extra or {}),
        "ranks": ranks,
    }
    _atomic_write(out_path, json.dumps(pm, indent=2, default=str))
    return pm
