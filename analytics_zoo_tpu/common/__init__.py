from .context import (NNContext, ZooTpuConfig, init_nncontext,
                      initNNContext, get_nncontext, reset_nncontext,
                      check_version)
