"""Context initialization: the TPU-native ``init_nncontext`` equivalent.

Parity surface: reference ``NNContext.initNNContext`` / python
``init_nncontext`` (zoo/.../common/NNContext.scala:132-206,
pyzoo/zoo/common/nncontext.py:21-40): conf injection + engine init + version
check.  On TPU the "context" is {platform, mesh, typed config}; there is no
SparkContext and no 5-layer conf sprawl (SURVEY §5 flags this) — one typed
``ZooTpuConfig`` object replaces bundled-conf-file + sys-props + env-var
layering.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, Optional

import jax

from ..parallel import distributed as dist_lib
from ..parallel import mesh as mesh_lib

log = logging.getLogger("analytics_zoo_tpu")

__version__ = "0.1.0"


@dataclasses.dataclass
class ZooTpuConfig:
    """Typed configuration (replaces spark-analytics-zoo.conf injection)."""

    app_name: str = "analytics-zoo-tpu"
    mesh_axes: Optional[Dict[str, int]] = None  # None -> all devices on data
    compute_dtype: str = "float32"  # "bfloat16" for MXU-native training
    seed: int = 0
    log_level: str = "INFO"
    version_check: bool = False  # parity: spark.analytics.zoo.versionCheck


class NNContext:
    """Holds the device mesh + config for a session."""

    def __init__(self, conf: ZooTpuConfig, mesh):
        self.conf = conf
        self.mesh = mesh
        self.app_name = conf.app_name

    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    @property
    def device_count(self):
        return len(self.devices)

    def __repr__(self):
        return (f"NNContext(app={self.app_name!r}, "
                f"platform={self.devices[0].platform}, "
                f"mesh={dict(self.mesh.shape)})")


_CONTEXT: Optional[NNContext] = None


def init_nncontext(conf: Optional[ZooTpuConfig] = None,
                   app_name: Optional[str] = None) -> NNContext:
    """Create (or return) the process-wide context.

    Mirrors the getOrCreate semantics of the reference
    (NNContext.scala:132-146): repeated calls return the same context.
    """
    global _CONTEXT
    if _CONTEXT is not None:
        return _CONTEXT
    if isinstance(conf, str):
        # reference parity: init_nncontext("App Name") treats a bare
        # string conf as the application name (nncontext.py:32-33)
        conf, app_name = None, app_name or conf
    conf = conf or ZooTpuConfig()
    if app_name:
        conf.app_name = app_name
    logging.basicConfig(level=getattr(logging, conf.log_level, logging.INFO))
    if conf.version_check:
        check_version()
    # join the pod-wide cluster BEFORE the first backend-initializing jax
    # call, when launcher/cloud env vars are present (the reference's
    # Engine.init-before-use ordering, NNContext.scala:132-146) — after
    # this, jax.devices() below is the GLOBAL device list and the mesh
    # spans every host in the pod
    dist_lib.maybe_initialize_distributed()
    mesh = mesh_lib.create_mesh(conf.mesh_axes)
    mesh_lib.set_default_mesh(mesh)
    log.info("initNNContext: process %d/%d, %d %s device(s), mesh %s",
             jax.process_index(), jax.process_count(),
             len(jax.devices()), jax.devices()[0].platform,
             dict(mesh.shape))
    _CONTEXT = NNContext(conf, mesh)
    return _CONTEXT


# parity alias with the scala camelCase entry point
initNNContext = init_nncontext


def get_nncontext() -> Optional[NNContext]:
    return _CONTEXT


def reset_nncontext():
    global _CONTEXT
    _CONTEXT = None
    mesh_lib.set_default_mesh(None)


def check_version():
    """Compile-time vs runtime version check parity
    (NNContext.scala:78-130 ZooBuildInfo)."""
    import jax as _jax
    log.info("analytics-zoo-tpu %s on jax %s", __version__, _jax.__version__)
    return __version__
