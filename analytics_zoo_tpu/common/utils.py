"""Small host-side utilities.

Parity surface: reference zoo/.../common/Utils.scala:32-70
(``listLocalFiles``, ``saveBytes``, ``logUsageErrorAndThrowException``)
and the log-redirection helpers of nncontext.py:37-38
(``redire_spark_logs`` / ``show_bigdl_info_logs`` — here there is no
Spark/BigDL log firehose, so the helpers manage the framework's own
logger)."""

from __future__ import annotations

import logging
import os
from typing import List

import numpy as np

log = logging.getLogger("analytics_zoo_tpu")


def pad_leading(batch, pad: int):
    """Zero-pad the leading (batch) axis of every array in ``batch`` (an
    array or tuple/list of arrays) by ``pad`` rows, PRESERVING dtype —
    integer embedding/gather ids must stay integer.  The single padding
    helper shared by the trainer's fixed-shape batch loops and the
    serving bucket cache."""
    if pad == 0:
        return batch

    def one(a):
        a = np.asarray(a)
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    if isinstance(batch, (tuple, list)):
        return tuple(one(a) for a in batch)
    return one(batch)


def list_local_files(path: str) -> List[str]:
    """Recursively list files under ``path`` (Utils.scala:32
    listLocalFiles/doListLocalFiles)."""
    out: List[str] = []
    for root, dirs, files in os.walk(path):
        dirs.sort()  # deterministic traversal across filesystems
        for f in sorted(files):
            out.append(os.path.join(root, f))
    return out


def save_bytes(data: bytes, path: str, is_overwrite: bool = False):
    """Write bytes to a local file (Utils.scala:52 saveBytes), refusing
    to clobber unless asked — same contract as the reference."""
    if os.path.exists(path) and not is_overwrite:
        raise FileExistsError(
            f"{path} already exists (pass is_overwrite=True to replace)")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def log_usage_error_and_throw(message: str):
    """Log then raise — the reference funnels user-facing usage errors
    through one chokepoint (Utils.scala:56)."""
    log.error(message)
    raise ValueError(message)


def redirect_logs(path: str, level: int = logging.INFO):
    """Send the framework's logs to a file (the reference's
    redire_spark_logs analog)."""
    handler = logging.FileHandler(path)
    handler.setLevel(level)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    log.addHandler(handler)
    # the logger itself must pass records down, or an unset logger
    # (inheriting root's WARNING) filters INFO before the handler sees it
    if log.level == logging.NOTSET or log.level > level:
        log.setLevel(level)
    return handler


def show_info_logs():
    """Raise framework log verbosity to INFO on stderr (the reference's
    show_bigdl_info_logs analog)."""
    log.setLevel(logging.INFO)
    # FileHandler subclasses StreamHandler — only a true console handler
    # satisfies this function's purpose
    if not any(type(h) is logging.StreamHandler for h in log.handlers):
        log.addHandler(logging.StreamHandler())
