"""Async host↔device prefetch: overlap batch k+1's host work with batch
k's device compute.

``jax.device_put`` is dispatch-asynchronous, but everything BEFORE it —
decode, shuffle-gather, ``np.stack``, tail padding — runs on the host and
serializes with the step loop unless it is moved off-thread.  PERF_NOTES
§"Host input pipeline" measures overlap efficiency 0.65 for the
synchronous put-then-step pattern: the host→device transfer plus batch
materialization is the end-to-end wall.  ``prefetch`` runs the source
iterator AND the transform (decode + ``device_put``) on a background
thread with a bounded buffer, so while the device computes batch *k* the
host is already materializing and shipping batch *k+1* (double-buffered
at the default ``depth=2``).

Used by the Trainer's fit/predict loops and by ``InferenceModel``'s
batch streaming; safe anywhere an iterator of batches feeds a compute
loop.  Ordering is preserved exactly; source exceptions re-raise at the
consumer at the position they occurred; abandoning the iterator
(``close()`` / GC / ``break``) stops the worker promptly.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

_END = object()
_ERR = object()


def _put(q: "queue.Queue", stop: threading.Event, item) -> bool:
    """Bounded put that stays responsive to close(); returns False when
    the consumer is gone."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


def _worker(source, transform, q, stop):
    try:
        for item in source:
            if stop.is_set():
                return
            if transform is not None:
                item = transform(item)
            if not _put(q, stop, (None, item)):
                return
        _put(q, stop, (_END, None))
    except BaseException as e:  # re-raised at the consumer
        _put(q, stop, (_ERR, e))


class PrefetchIterator:
    """Iterator pulling items through a background worker thread.

    ``transform`` (host decode + ``jax.device_put``) runs ON THE WORKER,
    so at most ``depth`` transformed items are in flight ahead of the
    consumer — bounded memory, double-buffered overlap at depth 2.
    """

    def __init__(self, iterable: Iterable, transform: Optional[Callable] = None,
                 depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # the worker closes over (source, transform, queue, stop) but NOT
        # self — a running thread referencing a bound method would keep
        # this iterator alive forever, so an abandoned iterator could
        # never be collected and its __del__/close never fire
        self._thread = threading.Thread(
            target=_worker, args=(iterable, transform, self._q, self._stop),
            name="zoo-prefetch", daemon=True)
        self._started = False
        self._done = False

    # ---- consumer side ----
    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if not self._started:
            self._started = True
            self._thread.start()
        kind, val = self._q.get()
        if kind is _END:
            self._done = True
            raise StopIteration
        if kind is _ERR:
            self._done = True
            self._stop.set()
            raise val
        return val

    def close(self):
        """Stop the worker and drop buffered items (idempotent)."""
        self._done = True
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch(iterable: Iterable, transform: Optional[Callable] = None,
             depth: int = 2) -> PrefetchIterator:
    """Prefetch ``iterable`` through a background thread.

    ``transform(item)`` — typically decode + ``jax.device_put`` — runs on
    the worker; ``depth`` bounds how many transformed items wait ahead of
    the consumer (2 = classic double buffering)."""
    return PrefetchIterator(iterable, transform=transform, depth=depth)
