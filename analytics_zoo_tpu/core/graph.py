"""Symbolic graph: ``Variable`` nodes + ``GraphModule`` evaluation.

One graph engine backs BOTH user-facing surfaces of the reference:

* the Keras functional API — ``Model(input, output)`` over layer calls
  (reference: zoo/.../pipeline/api/keras/models/Topology.scala:509-714), and
* the autograd DSL — ``Variable`` operator overloads, ``Parameter``,
  ``CustomLoss`` (reference: zoo/.../pipeline/api/autograd/math.scala:341-567).

The reference implements these as two distinct wrappers over BigDL graph
nodes whose "autodiff" is each wrapped module's hand-written backward.  Here a
``Variable`` is a lightweight symbolic node; a ``GraphModule`` topologically
evaluates the node graph as one pure JAX function, so ``jax.grad`` provides
real reverse-mode autodiff through arbitrary user expressions and the whole
graph jits into a single XLA computation.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import shapes as shape_utils
from .module import (Layer, Params, State, fresh_name, register_layer,
                     remat_apply, split_rng)

_NODE_IDS = itertools.count()


def broadcast_shapes(a, b):
    """Numpy-style broadcast of two batch shapes where ``None`` = unknown."""
    la, lb = len(a), len(b)
    n = max(la, lb)
    a = (1,) * (n - la) + tuple(a)
    b = (1,) * (n - lb) + tuple(b)
    out = []
    for da, db in zip(a, b):
        if da is None or db is None:
            out.append(None if (da in (1, None) and db in (1, None)) else
                       (da if da not in (1, None) else db))
        elif da == 1:
            out.append(db)
        elif db == 1 or da == db:
            out.append(da)
        else:
            raise ValueError(f"Cannot broadcast shapes {a} and {b}")
    return tuple(out)


class Variable:
    """A symbolic tensor: the output of a layer applied to other Variables."""

    def __init__(self, layer: Optional[Layer], inputs: Sequence["Variable"],
                 shape, name: Optional[str] = None):
        self.layer = layer
        self.inputs: Tuple["Variable", ...] = tuple(inputs)
        self.shape = tuple(shape)
        self.node_id = next(_NODE_IDS)
        self.name = name or (layer.name if layer is not None
                             else fresh_name("input"))

    # -- construction --------------------------------------------------
    @staticmethod
    def from_layer(layer: Layer, x) -> "Variable":
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        for v in xs:
            if not isinstance(v, Variable):
                raise TypeError(
                    f"Layer {layer.name} called on non-Variable {type(v)}; "
                    "wrap constants with autograd.constant()")
        in_shape = [v.shape for v in xs] if len(xs) > 1 else xs[0].shape
        out_shape = layer.compute_output_shape(in_shape)
        return Variable(layer, xs, out_shape)

    # -- graph traversal ----------------------------------------------
    def ancestors(self) -> List["Variable"]:
        """All nodes reachable from self, in topological order."""
        order, seen = [], set()

        def visit(v):
            if v.node_id in seen:
                return
            seen.add(v.node_id)
            for p in v.inputs:
                visit(p)
            order.append(v)

        visit(self)
        return order

    # -- operator overloads (implemented by ops.py via monkey-wiring) --
    def __add__(self, other):
        from ..ops import elementwise as E
        return E.add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        from ..ops import elementwise as E
        return E.sub(self, other)

    def __rsub__(self, other):
        from ..ops import elementwise as E
        return E.sub(other, self)

    def __mul__(self, other):
        from ..ops import elementwise as E
        return E.mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from ..ops import elementwise as E
        return E.div(self, other)

    def __rtruediv__(self, other):
        from ..ops import elementwise as E
        return E.div(other, self)

    def __neg__(self):
        from ..ops import elementwise as E
        return E.neg(self)

    def __pow__(self, p):
        from ..ops import elementwise as E
        return E.pow(self, p)

    def __getitem__(self, item):
        from ..ops import elementwise as E
        return E.getitem(self, item)

    # reference parity: Variable.slice / indexSelect / squeeze
    # (math.scala:484-530)
    def slice(self, dim, start_index, length):
        from ..ops import elementwise as E
        return E.slice(self, dim, start_index, length)

    def index_select(self, dim, index):
        from ..ops import elementwise as E
        return E.index_select(self, dim, index)

    def squeeze(self, dim):
        from ..ops import elementwise as E
        return E.squeeze(self, dim)

    def __repr__(self):
        return f"Variable({self.name}, shape={self.shape})"


@register_layer
class InputLayer(Layer):
    """Placeholder layer marking a graph input."""

    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs

    def get_config(self):
        return super().get_config()


def Input(shape, name=None) -> Variable:
    """Create a graph input Variable with per-sample ``shape``."""
    layer = InputLayer(input_shape=shape, name=name)
    return Variable(layer, (), shape_utils.to_batch_shape(shape),
                    name=layer.name)


class GraphModule(Layer):
    """A Layer evaluating a Variable graph from ``inputs`` to ``outputs``.

    Weight sharing falls out naturally: a layer instance appearing at several
    nodes contributes one params entry (keyed by its unique name).
    """

    stateful = True
    stochastic = True

    def __init__(self, inputs, outputs, name=None):
        super().__init__(name=name)
        self.input_vars: List[Variable] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.output_vars: List[Variable] = (
            list(outputs) if isinstance(outputs, (list, tuple)) else [outputs])
        self.single_output = not isinstance(outputs, (list, tuple))

        # topological order over the union of all output ancestries
        seen: Dict[int, Variable] = {}
        self.nodes: List[Variable] = []
        for out in self.output_vars:
            for v in out.ancestors():
                if v.node_id not in seen:
                    seen[v.node_id] = v
                    self.nodes.append(v)
        input_ids = {v.node_id for v in self.input_vars}
        for v in self.nodes:
            if not v.inputs and v.node_id not in input_ids and not isinstance(
                    v.layer, InputLayer) and not getattr(
                        v.layer, "is_source", False):
                raise ValueError(
                    f"Graph node {v.name} has no inputs and is not a graph "
                    "input / Parameter / constant")

        # one entry per distinct layer instance, in first-use order
        self.layers: List[Layer] = []
        layer_ids = set()
        for v in self.nodes:
            if v.layer is not None and id(v.layer) not in layer_ids \
                    and not isinstance(v.layer, InputLayer):
                layer_ids.add(id(v.layer))
                self.layers.append(v.layer)

    # ----- functional contract -----
    def init(self, rng, input_shape=None) -> Tuple[Params, State]:
        params: Params = {}
        state: State = {}
        rngs = split_rng(rng, max(len(self.layers), 1))
        # first-use input shape per layer instance
        shaped = {}
        for v in self.nodes:
            if v.layer is None or isinstance(v.layer, InputLayer):
                continue
            if id(v.layer) not in shaped:
                ins = ([p.shape for p in v.inputs] if len(v.inputs) > 1
                       else (v.inputs[0].shape if v.inputs else None))
                shaped[id(v.layer)] = ins
        for r, layer in zip(rngs, self.layers):
            p, s = layer.init(r, shaped[id(layer)])
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
        return params, state

    def init_params(self, rng, input_shape):  # pragma: no cover - init() used
        return self.init(rng, input_shape)[0]

    def call(self, params, state, inputs, training=False, rng=None):
        xs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
        if len(xs) != len(self.input_vars):
            raise ValueError(
                f"{self.name}: expected {len(self.input_vars)} inputs, "
                f"got {len(xs)}")
        values: Dict[int, Any] = {
            v.node_id: x for v, x in zip(self.input_vars, xs)}
        new_state = dict(state)
        rngs = iter(split_rng(rng, len(self.nodes)))
        for v in self.nodes:
            r = next(rngs)
            if v.node_id in values:
                continue
            if isinstance(v.layer, InputLayer):
                raise ValueError(
                    f"Graph input {v.name} was not fed "
                    f"(inputs given: {[iv.name for iv in self.input_vars]})")
            layer = v.layer
            ins = ([values[p.node_id] for p in v.inputs] if len(v.inputs) > 1
                   else (values[v.inputs[0].node_id] if v.inputs else ()))
            p = params.get(layer.name, {})
            if not layer.trainable and p:
                # frozen layer (trainable=False / freeze semantics): block
                # gradients so the optimizer never moves these weights
                p = jax.tree_util.tree_map(jax.lax.stop_gradient, p)
            s = state.get(layer.name, {})
            out, s_new = remat_apply(layer, p, s, ins, training=training,
                                     rng=r)
            if layer.stateful and s_new:
                prev = new_state.get(layer.name)
                if (prev is not None and prev is not s
                        and isinstance(s_new, dict)
                        and "aux_loss" in s_new and "aux_loss" in prev):
                    # shared layer instance called at multiple nodes:
                    # ACCUMULATE the differentiable penalty across calls
                    # (last-write would silently drop earlier calls'
                    # aux gradient, e.g. a shared SwitchMoE's balancing)
                    s_new = {**s_new,
                             "aux_loss": s_new["aux_loss"]
                             + prev["aux_loss"]}
                new_state[layer.name] = s_new
            values[v.node_id] = out
        outs = [values[v.node_id] for v in self.output_vars]
        return (outs[0] if self.single_output else outs), new_state

    def apply(self, params, state, inputs, training=False, rng=None):
        return self.call(params, state, inputs, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        if self.single_output:
            return self.output_vars[0].shape
        return [v.shape for v in self.output_vars]

    @property
    def input_shapes(self):
        return [v.shape for v in self.input_vars]

    @property
    def output_shapes(self):
        return [v.shape for v in self.output_vars]
