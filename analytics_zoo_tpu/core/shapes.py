"""Shape utilities for Keras-1-style shape inference.

The reference framework infers output shapes layer-by-layer from a
``build(inputShape) -> outputShape`` contract (reference:
zoo/.../pipeline/api/keras/models/Topology.scala:722-742).  Here shapes are
plain tuples whose leading batch dimension is ``None``; all inference is done
eagerly in Python so that the resulting JAX program has fully static shapes
(an XLA requirement for TPU compilation).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

Shape = Tuple[Optional[int], ...]


def to_batch_shape(input_shape: Sequence[Optional[int]]) -> Shape:
    """Prepend a ``None`` batch dim to a per-sample shape."""
    return (None,) + tuple(int(d) for d in input_shape)


def drop_batch(shape: Shape) -> Tuple[int, ...]:
    return tuple(shape[1:])


def is_shape(x) -> bool:
    return isinstance(x, (tuple, list)) and all(
        d is None or isinstance(d, int) for d in x
    )


def merge_batch(shapes: Sequence[Shape]) -> Optional[int]:
    """Return the common batch dim of several shapes (None if unknown)."""
    batch = None
    for s in shapes:
        if s and s[0] is not None:
            if batch is not None and batch != s[0]:
                raise ValueError(f"Incompatible batch dims: {batch} vs {s[0]}")
            batch = s[0]
    return batch


def conv_output_length(
    input_length: Optional[int],
    filter_size: int,
    border_mode: str,
    stride: int,
    dilation: int = 1,
) -> Optional[int]:
    """Keras-1 convolution length arithmetic (border_mode in {same, valid, full, causal})."""
    if input_length is None:
        return None
    dilated = filter_size + (filter_size - 1) * (dilation - 1)
    if border_mode in ("same", "causal"):
        out = input_length
    elif border_mode == "valid":
        out = input_length - dilated + 1
    elif border_mode == "full":
        out = input_length + dilated - 1
    else:
        raise ValueError(f"Unknown border_mode {border_mode!r}")
    result = (out + stride - 1) // stride
    if result <= 0:
        raise ValueError(
            f"Convolution output length is {result} (input {input_length}, "
            f"filter {filter_size}, stride {stride}, {border_mode}): input "
            "too small for this layer stack")
    return result


def deconv_output_length(
    input_length: Optional[int], filter_size: int, border_mode: str, stride: int
) -> Optional[int]:
    if input_length is None:
        return None
    out = input_length * stride
    if border_mode == "valid":
        out += max(filter_size - stride, 0)
    return out


def pool_output_length(
    input_length: Optional[int], pool_size: int, border_mode: str, stride: int
) -> Optional[int]:
    if input_length is None:
        return None
    if border_mode == "same":
        result = math.ceil(input_length / stride)
    else:
        result = (input_length - pool_size) // stride + 1
    if result <= 0:
        raise ValueError(
            f"Pooling output length is {result} (input {input_length}, "
            f"pool {pool_size}, stride {stride}, {border_mode}): input "
            "too small for this layer stack")
    return result


def normalize_tuple(value, n: int, name: str = "value") -> Tuple[int, ...]:
    """Accept int or length-n sequence; return an n-tuple of ints."""
    if isinstance(value, int):
        return (value,) * n
    value = tuple(int(v) for v in value)
    if len(value) != n:
        raise ValueError(f"{name} must be an int or length-{n} tuple, got {value}")
    return value


def normalize_data_format(value: Optional[str]) -> str:
    """Map Keras-1 dim_ordering / Keras-2 data_format spellings to canonical form.

    TPU-native default is channels_last (NHWC maps cleanly onto XLA:TPU
    convolution layouts); ``th``/``channels_first`` inputs are accepted for
    API parity with the reference and transposed at the layer boundary.
    """
    if value is None:
        return "channels_last"
    v = value.lower()
    if v in ("tf", "channels_last", "nhwc"):
        return "channels_last"
    if v in ("th", "channels_first", "nchw"):
        return "channels_first"
    raise ValueError(f"Unknown data format {value!r}")
