"""Weight initializers (Keras-1 ``init=`` string surface).

Reference exposes these as string args on every layer
(e.g. ``init="glorot_uniform"`` on Dense, reference:
zoo/.../pipeline/api/keras/layers/Dense.scala).  Implemented directly over
``jax.random`` so inits run on-device and are jit-safe.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _fans(shape):
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) in (3, 4, 5):
        receptive = int(np.prod(shape[:-2]))
        fan_in = shape[-2] * receptive
        fan_out = shape[-1] * receptive
    else:
        fan_in = fan_out = int(np.sqrt(np.prod(shape)))
    return fan_in, fan_out


def glorot_uniform(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = np.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def glorot_normal(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    std = np.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(rng, shape, dtype)


def he_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return np.sqrt(2.0 / fan_in) * jax.random.normal(rng, shape, dtype)


def he_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = np.sqrt(6.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def lecun_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = np.sqrt(3.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def uniform(rng, shape, dtype=jnp.float32, scale=0.05):
    return jax.random.uniform(rng, shape, dtype, -scale, scale)


def normal(rng, shape, dtype=jnp.float32, scale=0.05):
    return scale * jax.random.normal(rng, shape, dtype)


def zero(rng, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def one(rng, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def identity(rng, shape, dtype=jnp.float32):
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError("identity init requires a square 2D shape")
    return jnp.eye(shape[0], dtype=dtype)


def orthogonal(rng, shape, dtype=jnp.float32):
    flat = (shape[0], int(np.prod(shape[1:])))
    a = jax.random.normal(rng, flat, jnp.float32)
    q, r = jnp.linalg.qr(a.T if flat[0] < flat[1] else a)
    q = q * jnp.sign(jnp.diagonal(r))
    q = q.T if flat[0] < flat[1] else q
    return q.reshape(shape).astype(dtype)


_INITS = {
    "glorot_uniform": glorot_uniform,
    "glorot_normal": glorot_normal,
    "xavier": glorot_uniform,
    "he_normal": he_normal,
    "he_uniform": he_uniform,
    "lecun_uniform": lecun_uniform,
    "uniform": uniform,
    "normal": normal,
    "gaussian": normal,
    "zero": zero,
    "zeros": zero,
    "one": one,
    "ones": one,
    "identity": identity,
    "orthogonal": orthogonal,
}


def get(name):
    """Resolve an initializer by name (or pass a callable through)."""
    if callable(name):
        return name
    try:
        return _INITS[name]
    except KeyError:
        raise ValueError(f"Unknown initializer {name!r}; known: {sorted(_INITS)}")
