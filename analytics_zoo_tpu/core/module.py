"""Layer: the functional module contract of the TPU-native framework.

The reference framework's layer contract is an object-oriented
forward/backward pair (``updateOutput`` / ``updateGradInput`` /
``accGradParameters``; reference: zoo/.../pipeline/api/net/TFNet.scala:201-417
implements it for the TF bridge, every Keras layer wraps a BigDL module with
it).  On TPU the backward pass comes from ``jax.grad``, so a layer here is a
pure pair:

    init(rng, input_shape)                  -> (params, state)
    apply(params, state, inputs, training, rng) -> (outputs, new_state)

``params`` are trainable pytrees, ``state`` holds non-trained buffers
(BatchNorm moving stats).  Both are plain dicts of jnp arrays so the whole
model is a pytree that `jit`/`pjit` shard transparently.

Shape inference stays eager-Python (``compute_output_shape``) so every traced
program has static shapes — the precondition for MXU-friendly XLA tiling.
"""

from __future__ import annotations

import collections
import contextlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from . import shapes as shape_utils

Params = Dict[str, Any]
State = Dict[str, Any]

_LAYER_REGISTRY: Dict[str, type] = {}
_NAME_COUNTERS: "collections.Counter" = collections.Counter()


def register_layer(cls):
    """Class decorator: register for config-based (de)serialization.

    Layers whose class name collides with another registered layer (the
    keras2 skin reuses keras1 names) set ``serial_name`` to register and
    serialize under a distinct key."""
    _LAYER_REGISTRY[getattr(cls, "serial_name", None) or cls.__name__] = cls
    return cls


def serial_class_name(layer) -> str:
    """Registry key a layer instance serializes under."""
    return getattr(layer, "serial_name", None) or type(layer).__name__


def get_layer_class(name: str) -> type:
    if name not in _LAYER_REGISTRY and name.startswith("Keras2"):
        # keras2 registers on import; a saved keras2 model must load even
        # when the serving process never imported the keras2 package
        import importlib

        importlib.import_module("analytics_zoo_tpu.pipeline.api.keras2")
    if name not in _LAYER_REGISTRY:
        raise KeyError(
            f"Unknown layer class {name!r}; known: {sorted(_LAYER_REGISTRY)}"
        )
    return _LAYER_REGISTRY[name]


_SCOPE_STACK: list = []


def fresh_name(prefix: str) -> str:
    if _SCOPE_STACK:
        scope, counter = _SCOPE_STACK[-1]
        counter[prefix] += 1
        return f"{scope}/{prefix}_{counter[prefix]}"
    _NAME_COUNTERS[prefix] += 1
    return f"{prefix}_{_NAME_COUNTERS[prefix]}"


@contextlib.contextmanager
def name_scope(scope: str):
    """Deterministic layer naming: inside the scope, auto-names restart
    from a scope-local counter (``<scope>/<type>_<k>``), so rebuilding the
    same architecture yields identical parameter keys in ANY process.
    Without this, checkpoint keys depend on how many layers the saving
    process happened to create earlier — weights saved from a ZooModel
    could not be restored into a freshly built copy (the lexicographic
    order of ``conv_9`` vs ``conv_10`` flips the flattened leaf order)."""
    _SCOPE_STACK.append((scope, collections.Counter()))
    try:
        yield
    finally:
        _SCOPE_STACK.pop()


def pop_base_flags(config: dict) -> tuple:
    """Remove the base-``Layer``-managed attributes from a config dict.
    Every ``from_config`` (the base one and wrapper overrides that call
    ``cls(**config)`` themselves) must pop these — subclass __init__s
    don't take **kwargs, so a leftover key is a TypeError."""
    return config.pop("trainable", True), config.pop("remat", False)


def set_base_flags(obj: "Layer", flags: tuple) -> "Layer":
    obj.trainable, obj.remat = flags
    return obj


def remat_apply(layer, params, state, inputs, training=False, rng=None,
                force=False):
    """Apply ``layer`` honoring its ``remat`` flag.

    The graph executor routes every graph-node application through this
    (core/graph.py), and WRAPPERS that apply an inner layer themselves
    (TimeDistributed, Bidirectional) route the inner application through
    it too — so a remat flag works wherever the layer sits, not only at
    graph nodes.  ``force=True`` remats regardless of the layer's own
    flag (Bidirectional extends the user's forward-layer flag to the
    internally-built backward clone without clobbering a flag set on
    the clone directly)."""
    if (force or getattr(layer, "remat", False)) and training:
        # jax.checkpoint: save only this layer's boundary values,
        # recompute its internals in the backward pass (exact — the
        # FLOPs-for-HBM long-context trade; Layer(remat=...))
        def _rematted(p_, s_, ins_, r_):
            return layer.apply(p_, s_, ins_, training=True, rng=r_)

        return jax.checkpoint(_rematted)(params, state, inputs, rng)
    return layer.apply(params, state, inputs, training=training, rng=rng)


class Layer:
    """Base class for all layers.

    Subclasses implement ``init_params``, ``call`` and
    ``compute_output_shape``; stateful layers additionally use
    ``init_state`` and return updated state from ``call``.
    """

    #: set True on layers whose ``call`` consumes an rng when training
    stochastic: bool = False
    #: set True on layers carrying non-trainable state (e.g. BatchNorm)
    stateful: bool = False
    #: override when the class name collides with another registered layer
    serial_name: Optional[str] = None

    def __init__(self, input_shape=None, name: Optional[str] = None, **kwargs):
        self.name = name or fresh_name(type(self).__name__.lower())
        self.batch_input_shape: Optional[shape_utils.Shape] = (
            shape_utils.to_batch_shape(input_shape) if input_shape else None
        )
        self.trainable = kwargs.pop("trainable", True)
        # remat=True wraps this layer's training-mode application in
        # jax.checkpoint: its internal activations are recomputed during
        # the backward pass instead of saved — the standard FLOPs-for-
        # HBM trade for long-context / deep stacks.  Exact, not an
        # approximation.  Honored via remat_apply() both at graph nodes
        # (core/graph.py) and inside wrappers (TimeDistributed /
        # Bidirectional route their inner application through it).
        self.remat = kwargs.pop("remat", False)
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unexpected kwargs {kwargs}")

    # ---- symbolic graph building (functional API / autograd DSL) ----
    def __call__(self, x):
        from .graph import Variable  # local import to avoid cycle

        return Variable.from_layer(self, x)

    # ---- functional contract ----
    def init(self, rng, input_shape) -> Tuple[Params, State]:
        self._check_input_shape(input_shape)
        params = self.init_params(rng, input_shape)
        state = self.init_state(input_shape)
        return params, state

    def init_params(self, rng, input_shape) -> Params:
        return {}

    def init_state(self, input_shape) -> State:
        return {}

    def apply(self, params, state, inputs, training: bool = False, rng=None):
        out = self.call(params, state, inputs, training=training, rng=rng)
        if self.stateful:
            return out  # (outputs, new_state)
        return out, state

    def call(self, params, state, inputs, training: bool = False, rng=None):
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        return input_shape

    def _check_input_shape(self, input_shape):
        pass

    # ---- serialization ----
    def get_config(self) -> dict:
        cfg = {"name": self.name}
        if self.batch_input_shape is not None:
            cfg["input_shape"] = list(self.batch_input_shape[1:])
        if not self.trainable:
            # persist freezes (fine-tuned models reload still frozen);
            # omitted when True so existing configs stay byte-stable
            cfg["trainable"] = False
        if self.remat:
            cfg["remat"] = True  # omitted when False (byte-stability)
        return cfg

    @classmethod
    def from_config(cls, config: dict) -> "Layer":
        config = dict(config)
        flags = pop_base_flags(config)
        obj = cls(**config)
        return set_base_flags(obj, flags)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"

    # ---- parameter bookkeeping helpers ----
    def param_count(self, params: Params) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def split_rng(rng, n: int):
    if rng is None:
        return [None] * n
    return list(jax.random.split(rng, n))
