from .module import Layer, register_layer, get_layer_class
from .graph import Variable, Input, GraphModule
from . import shapes, initializers
