"""Training step profiler: the serving span discipline applied to
``Trainer.fit``.

Serving got a gap-free per-request phase taxonomy in the observability
PR; training steps had nothing — a slow fit could be input-bound,
upload-bound, compute-bound, or checkpoint-bound and the epoch wall
time would not say which.  When enabled, every step carries one
:class:`~..observability.trace.Span` over the phase chain
(``trace.TRAIN_PHASES``)::

    data_wait -> h2d -> grad_accum -> step_compute -> ckpt_save

* ``data_wait`` — the loop thread blocked on the prefetch queue (input
  pipeline can't keep up when this dominates);
* ``h2d`` — the host->device upload, measured ON the prefetch thread
  (it overlaps compute by design) and attributed to the consuming
  step via :meth:`Span.phase_add`;
* ``grad_accum`` — the host-side (accum, micro, ...) microbatch split
  when gradient accumulation is on (also prefetch-thread-measured; the
  device-side scan itself is inside ``step_compute`` — it is ONE
  compiled program);
* ``step_compute`` — the compiled step dispatch; the span is ACTIVE
  here, so XLA ``backend_compile`` events (profile.py hooks) attribute
  to the exact step that paid the compile;
* ``ckpt_save`` — the checkpoint write when its trigger fires.

Per-phase durations feed :class:`LatencyWindow` percentile families —
``zoo_train_step_seconds{phase=...}`` summaries — and an opt-in
bounded step timeline (JSONL, atomic publish) for offline inspection.
Step spans also land in the flight recorder when one is configured,
so a postmortem shows the dead worker's final steps phase by phase.

Enablement: ``Trainer.enable_step_profiler()`` or the env contract
(``ZOO_STEP_PROFILE=1``, ``ZOO_STEP_TIMELINE=/path.jsonl``) read at
``fit`` entry.  Cost when off: one ``None`` check per step.  Cost when
on: bounded by the faulttrain drill's interleaved >= 0.95x step-rate
gate.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import envcontract
from ..observability.metrics import (Family, LatencyWindow,
                                     summary_family)
from ..observability.trace import TRAIN_PHASES, Span

ENV_PROFILE = "ZOO_STEP_PROFILE"
ENV_TIMELINE = "ZOO_STEP_TIMELINE"

#: step entries per batched flight-recorder write (finish_step)
_FLUSH_EVERY = 32


def from_env() -> "Optional[StepProfiler]":
    """A profiler per the env contract, or None when not requested."""
    if not envcontract.env_flag(ENV_PROFILE) \
            and not envcontract.env_flag(ENV_TIMELINE):
        return None
    return StepProfiler(
        timeline_path=envcontract.env_str(ENV_TIMELINE))


class StepProfiler:
    """Per-phase aggregation + optional timeline for one trainer's fit
    loops (module docstring).

    Writes happen on the training loop thread; ``families()`` may be
    called from a scrape/snapshot thread — the windows are internally
    locked and the counters are GIL-atomic ints."""

    def __init__(self, timeline_path: Optional[str] = None,
                 window: int = 2048, timeline_cap: int = 4096):
        # compile attribution rides the existing XLA monitoring hooks:
        # with a profile installed, a backend_compile firing while a
        # step span is active lands as an event ON that span
        from ..observability import profile as xla_profile
        try:
            xla_profile.install()
        except Exception:
            pass  # profiling works without compile attribution
        self.windows: Dict[str, LatencyWindow] = {
            p: LatencyWindow(window) for p in TRAIN_PHASES}
        self.timeline_path = timeline_path
        self.steps = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        self._timeline: "deque[Dict[str, Any]]" = deque(maxlen=timeline_cap)
        self._tl_lock = threading.Lock()
        # step entries awaiting a batched flight-recorder flush
        # (single-writer: the training loop thread)
        self._pending: List[Dict[str, Any]] = []
        # the wrapped data iterator stashes the wait it measured here;
        # single-writer (the loop thread) by construction
        self.last_wait_s = 0.0

    # ------------------------------------------------------- loop hooks
    def timed_iter(self, it):
        """Wrap the device-batch iterator so the time the loop thread
        spends blocked in ``next()`` is captured as ``data_wait``.
        Plain generator — ``close()`` is forwarded by the caller
        closing the underlying iterator directly."""
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self.last_wait_s = time.perf_counter() - t0
            yield item

    def begin_step(self, step: int, h2d_s: float,
                   accum_s: float = 0.0) -> Span:
        """Open the step span with the pre-measured cross-thread
        phases: the just-observed queue wait, the prefetch thread's
        upload for this batch, and (under gradient accumulation) its
        host-side microbatch split."""
        span = Span(None, "train_step", labels={"step": step})
        span.phase_add("data_wait", self.last_wait_s)
        span.phase_add("h2d", h2d_s)
        if accum_s:
            span.phase_add("grad_accum", accum_s)
        return span

    def finish_step(self, span: Span, step: int) -> None:
        """Close the span, fold its phases into the windows, append
        the timeline entry, and offer it to the flight recorder."""
        span.finish()
        totals = span.phase_totals()
        for phase, dur in totals.items():
            win = self.windows.get(phase)
            if win is not None:
                win.add(dur)
        compiles = [e for e in span.events
                    if e.get("name") == "backend_compile"]
        self.steps += 1
        entry = {"step": step,
                 **{f"{p}_ms": round(totals.get(p, 0.0) * 1e3, 4)
                    for p in TRAIN_PHASES},
                 "wall_ms": round(span.wall_s * 1e3, 4)}
        if compiles:
            compile_s = sum(float(e.get("seconds") or 0.0)
                            for e in compiles)
            self.compiles += len(compiles)
            self.compile_seconds += compile_s
            entry["compiles"] = len(compiles)
            entry["compile_ms"] = round(compile_s * 1e3, 3)
        with self._tl_lock:
            self._timeline.append(entry)
        from ..observability import flightrec
        rec = flightrec.current()
        if rec is not None:
            # rich phase entries are BATCHED (one framed write per
            # _FLUSH_EVERY steps): per-step write-through belongs to
            # the trainer's tiny hb liveness marker alone — a crash
            # loses at most this buffer of phase detail, never the
            # "last completed step"
            self._pending.append({"t": "step",
                                  "ts": round(time.time(), 6), **entry})
            if len(self._pending) >= _FLUSH_EVERY:
                self.flush(rec)

    def flush(self, rec=None) -> None:
        """Write buffered step entries to the flight recorder (the
        trainer also calls this at fit end so short fits lose
        nothing)."""
        if rec is None:
            from ..observability import flightrec
            rec = flightrec.current()
        pending, self._pending = self._pending, []
        if rec is not None and pending:
            rec.record_batch(pending)

    # -------------------------------------------------------- read side
    def snapshot(self) -> Dict[str, Any]:
        return {"steps": self.steps, "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 6),
                "phases": {p: w.snapshot()
                           for p, w in self.windows.items()
                           if w.count}}

    def families(self) -> List[Family]:
        """``zoo_train_step_seconds{phase=...}`` percentile summaries
        (one family; render merges the per-phase pieces) + compile
        attribution counters.  A registry/flight-recorder collector."""
        fams: List[Family] = []
        for phase, win in self.windows.items():
            fam = summary_family(
                "zoo_train_step_seconds",
                "per-phase training step seconds (stepprof taxonomy)",
                {"phase": phase}, win.snapshot())
            if fam is not None:
                fams.append(fam)
        fams.append(Family(
            "counter", "zoo_train_step_compiles_total",
            "XLA compiles attributed to profiled training steps",
            [({}, self.compiles)]))
        return fams

    def timeline(self) -> List[Dict[str, Any]]:
        with self._tl_lock:
            return list(self._timeline)

    def write_timeline(self, path: Optional[str] = None) -> Optional[str]:
        """Publish the step timeline as JSONL (the shared
        tmp+fsync+atomic-rename discipline; the artifact is always
        complete).  No-op without a path."""
        path = path or self.timeline_path
        if not path:
            return None
        from ..observability.flightrec import atomic_write
        atomic_write(path, "".join(
            json.dumps(e, separators=(",", ":")) + "\n"
            for e in self.timeline()))
        return path
