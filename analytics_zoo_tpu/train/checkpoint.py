"""Checkpoint / resume: epoch-triggered training-state snapshots.

Parity surface: ``setCheckpoint(path, overWrite)`` + epoch-trigger snapshots
(reference: Topology.scala:184-194, NNEstimator.scala:301-307) and
saveModel/loadModel weight round-trips (ZooModel.scala:78-82).

Format: one ``.npz`` of flattened leaves (keyed by pytree path) + a JSON
manifest.  Restore fills a template pytree (obtained from a fresh init), so
arbitrary optax states round-trip without pickling.  Saves can run on a
background thread (``async_save``) — the TPU keeps training while the host
writes, which is the failure-recovery story SURVEY §5 prescribes for SPMD
(no Spark lineage to lean on).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import threading
from typing import Any, Optional

import numpy as np
import jax


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names, leaves = [], []
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        names.append(name or "leaf")
        leaves.append(np.asarray(leaf))
    return names, leaves, treedef


def save_checkpoint(directory: str, tag: Any, tree, overwrite: bool = True,
                    meta: Optional[dict] = None) -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{tag}.npz")
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists and overwrite=False "
                              "(reference setCheckpoint overWrite semantics)")
    names, leaves, _ = _flatten_with_names(tree)
    arrays = {f"arr_{i}": leaf for i, leaf in enumerate(leaves)}
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    manifest = {"names": names, "tag": str(tag), "meta": meta or {}}
    with open(os.path.join(directory, f"ckpt_{tag}.json"), "w") as f:
        json.dump(manifest, f)
    return path


_PENDING: list = []


def async_save(directory: str, tag: Any, tree, meta: Optional[dict] = None):
    """Snapshot leaves to host (device_get) then write on a daemon thread."""
    host_tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
    t = threading.Thread(
        target=save_checkpoint, args=(directory, tag, host_tree),
        kwargs={"meta": meta}, daemon=True)
    t.start()
    _PENDING.append((os.path.abspath(directory), t))
    return t


def wait_pending(directory: Optional[str] = None):
    """Join in-flight writers — all of them, or only those targeting
    ``directory`` (so one trainer's fit never blocks on another
    trainer's multi-GB snapshot)."""
    want = None if directory is None else os.path.abspath(directory)
    remaining = []
    while _PENDING:
        d, t = _PENDING.pop()
        if want is None or d == want:
            t.join()
        else:
            remaining.append((d, t))
    _PENDING.extend(remaining)


# daemon writer threads die with the interpreter; without this a short
# script can exit before its last epoch checkpoint finishes writing
atexit.register(wait_pending)


def latest_tag(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    tags = []
    for f in os.listdir(directory):
        if f.endswith(".tmp.npz"):  # in-flight/aborted atomic write
            continue
        m = re.match(r"ckpt_(.+)\.npz$", f)
        if m:
            tags.append(m.group(1))
    if not tags:
        return None

    def key(t):
        m = re.search(r"(\d+)$", t)
        return int(m.group(1)) if m else -1

    return max(tags, key=key)


def restore_checkpoint(directory: str, template, tag: Any = None):
    """Load ``ckpt_<tag>`` into the structure of ``template``."""
    tag = tag if tag is not None else latest_tag(directory)
    if tag is None:
        raise FileNotFoundError(f"No checkpoints in {directory}")
    path = os.path.join(directory, f"ckpt_{tag}.npz")
    data = np.load(path)
    leaves = [data[f"arr_{i}"] for i in range(len(data.files))]
    flat, treedef = jax.tree_util.tree_flatten(template)
    if len(flat) != len(leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} leaves, template has {len(flat)}")
    for tmpl, loaded in zip(flat, leaves):
        if np.shape(tmpl) != loaded.shape:
            raise ValueError(
                f"Leaf shape mismatch: {np.shape(tmpl)} vs {loaded.shape}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def read_meta(directory: str, tag: Any = None) -> dict:
    tag = tag if tag is not None else latest_tag(directory)
    with open(os.path.join(directory, f"ckpt_{tag}.json")) as f:
        return json.load(f).get("meta", {})
