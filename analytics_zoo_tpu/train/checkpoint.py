"""Checkpoint / resume: epoch-triggered training-state snapshots.

Parity surface: ``setCheckpoint(path, overWrite)`` + epoch-trigger snapshots
(reference: Topology.scala:184-194, NNEstimator.scala:301-307) and
saveModel/loadModel weight round-trips (ZooModel.scala:78-82).

Two formats:

* Flat (``save_checkpoint``): one ``.npz`` of flattened leaves (keyed by
  pytree path) + a JSON manifest.  Restore fills a template pytree
  (obtained from a fresh init), so arbitrary optax states round-trip
  without pickling.
* Sharded (``save_sharded``): each process writes ONLY its addressable,
  replica-0 device shards (``ckpt_<tag>.shard-p<rank>.npz``) — no
  host-0 gather, bounded host memory, and the natural multi-host format
  (every pod process writes in parallel to a shared filesystem).  Restore
  reassembles global leaves from all shard files and re-places them under
  *target* shardings, so a checkpoint taken on one mesh shape restores
  onto a different one (fsdp → pure-data, 8 devices → 4, ...).

Saves can run on a background thread (``async_save``/
``async_save_sharded``) — the TPU keeps training while the host writes,
which is the failure-recovery story SURVEY §5 prescribes for SPMD (no
Spark lineage to lean on).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Optional, Tuple

import numpy as np
import jax

from . import faults
from . import metrics as train_metrics
from ..observability.log import get_logger

_log = get_logger("analytics_zoo_tpu.train.checkpoint")


def _path_name(path) -> str:
    """THE canonical leaf-path -> name derivation.  Save-side manifests
    and restore-side templates must agree exactly (the by-name
    structure-evolution restore matches on these strings), so every
    site derives names through this one function."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path) or "leaf"


def _flatten_with_names(tree):
    """Leaves are returned AS-IS (no host transfer) — sharded leaves of a
    pod-wide array must not be gathered here."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = [_path_name(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def save_checkpoint(directory: str, tag: Any, tree, overwrite: bool = True,
                    meta: Optional[dict] = None) -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{tag}.npz")
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists and overwrite=False "
                              "(reference setCheckpoint overWrite semantics)")
    names, leaves, _ = _flatten_with_names(tree)
    arrays = {f"arr_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    manifest = {"names": names, "tag": str(tag), "meta": meta or {}}
    with open(os.path.join(directory, f"ckpt_{tag}.json"), "w") as f:
        json.dump(manifest, f)
    # the commit manifest is the LAST write: its atomic rename is the
    # one event that makes this tag restorable
    _write_commit(directory, tag,
                  [f"ckpt_{tag}.npz", f"ckpt_{tag}.json"], 1)
    train_metrics.record_ckpt_save("flat")
    return path


_PENDING: list = []


def async_save(directory: str, tag: Any, tree, meta: Optional[dict] = None):
    """Snapshot leaves to host (device_get) then write on a daemon thread."""
    host_tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
    t = threading.Thread(
        target=save_checkpoint, args=(directory, tag, host_tree),
        kwargs={"meta": meta}, daemon=True)
    t.start()
    _PENDING.append((os.path.abspath(directory), t))
    return t


def wait_pending(directory: Optional[str] = None):
    """Join in-flight writers — all of them, or only those targeting
    ``directory`` (so one trainer's fit never blocks on another
    trainer's multi-GB snapshot)."""
    want = None if directory is None else os.path.abspath(directory)
    remaining = []
    while _PENDING:
        d, t = _PENDING.pop()
        if want is None or d == want:
            t.join()
        else:
            remaining.append((d, t))
    _PENDING.extend(remaining)


# daemon writer threads die with the interpreter; without this a short
# script can exit before its last epoch checkpoint finishes writing
atexit.register(wait_pending)


# --------------------------------------------------- commit protocol ----
#
# Crash safety: a checkpoint directory is only as trustworthy as its
# *last complete* member.  An async save interrupted by a crash leaves
# shard files half-written (or some processes' shards missing entirely)
# under a perfectly plausible tag — blind newest-tag selection would
# restore torn state.  Every save therefore ends with a COMMIT MANIFEST
# (``ckpt_<tag>.commit.json``): per-file byte sizes + sha256, written
# tmp+atomic-rename as the final step (execstore-style).  Selection
# only considers committed tags; restore re-verifies the checksums and
# discards a tag that fails them, falling back to the newest complete
# one.  A crash may cost lost steps — never a wrong or torn restore.

_COMMIT_VERSION = 1
_COMMIT_WAIT_S = 120.0  # async pod commit: shared-fs wait for all shards


def _commit_path(directory: str, tag: Any) -> str:
    return os.path.join(directory, f"ckpt_{tag}.commit.json")


def _digest_file(path: str, chunk: int = 1 << 20) -> Tuple[int, str]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            size += len(block)
            h.update(block)
    return size, h.hexdigest()


def _write_commit(directory: str, tag: Any, filenames, n_processes: int):
    files = {}
    for fn in filenames:
        size, sha = _digest_file(os.path.join(directory, fn))
        files[fn] = {"bytes": size, "sha256": sha}
    payload = {"version": _COMMIT_VERSION, "tag": str(tag),
               "n_processes": n_processes, "files": files}
    path = _commit_path(directory, tag)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    train_metrics.record_ckpt_commit()


def read_commit(directory: str, tag: Any) -> Optional[dict]:
    """The commit manifest for ``tag``, or None when the tag was never
    committed (torn/in-flight save, or a pre-commit-protocol save)."""
    try:
        with open(_commit_path(directory, tag)) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload.get("files"), dict):
        return None
    return payload


def verify_commit(directory: str, tag: Any,
                  deep: bool = False) -> Tuple[bool, str]:
    """Check every file the commit manifest covers.  Shallow (selection
    time): presence + byte size.  ``deep`` (restore time): full sha256
    — a bit-flipped shard is convicted here."""
    commit = read_commit(directory, tag)
    if commit is None:
        return False, "no commit manifest"
    for fn, rec in commit["files"].items():
        path = os.path.join(directory, fn)
        try:
            size = os.path.getsize(path)
        except OSError:
            return False, f"{fn} missing"
        if size != rec.get("bytes"):
            return False, (f"{fn} is {size} bytes, commit recorded "
                           f"{rec.get('bytes')}")
        if deep:
            _, sha = _digest_file(path)
            if sha != rec.get("sha256"):
                return False, f"{fn} sha256 mismatch"
    return True, "ok"


def discard_tag(directory: str, tag: Any) -> None:
    """Delete every file of ``tag`` (a corrupt/torn checkpoint must not
    be re-selected — or re-verified — on the next restore).  Races with
    another pod process discarding the same tag are benign."""
    tag_re = re.escape(str(tag))
    pats = [rf"ckpt_{tag_re}(\.shard-p\d+)?\.npz(\.tmp\.npz)?$",
            rf"ckpt_{tag_re}\.json$",
            rf"ckpt_{tag_re}\.commit\.json(\.tmp)?$"]
    for f in os.listdir(directory):
        if any(re.match(p, f) for p in pats):
            try:
                os.remove(os.path.join(directory, f))
            except OSError:
                pass  # another process won the race


def _all_tags(directory: str) -> set:
    tags = set()
    for f in os.listdir(directory):
        if f.endswith(".tmp.npz"):  # in-flight/aborted atomic write
            continue
        m = re.match(r"ckpt_(.+?)(\.shard-p\d+)?\.npz$", f)
        if m:
            tags.add(m.group(1))
    return tags


def _numeric_tag_key(t):
    m = re.search(r"(\d+)$", t)
    return int(m.group(1)) if m else -1


def latest_tag(directory: str) -> Optional[str]:
    """Newest COMPLETE tag: only tags with a (shallow-)valid commit
    manifest are candidates — a tag whose shards exist but whose commit
    never landed is an in-flight/torn save and is skipped.  Directories
    written before the commit protocol (no manifest on ANY tag) keep
    the legacy newest-tag behavior so old checkpoints stay loadable."""
    if not os.path.isdir(directory):
        return None
    tags = _all_tags(directory)
    if not tags:
        return None
    committed = {t for t in tags if read_commit(directory, t) is not None}
    if committed:
        candidates = [t for t in committed
                      if verify_commit(directory, t)[0]]
        if not candidates:
            return None  # every committed tag is damaged: cold start
    else:
        candidates = sorted(tags)  # legacy (pre-commit) directory
    return max(candidates, key=_numeric_tag_key)


def _resolve_tag(directory: str, tag: Any):
    """The restore-side tag selection + verification loop.

    Explicit ``tag``: deep-verify when committed (legacy uncommitted
    tags pass through — the caller asked for exactly this one) and
    raise on mismatch.  ``tag=None``: newest complete tag, deep-verified;
    a tag failing its checksums is DELETED and selection falls back to
    the next newest complete one — repeat until a verified tag or a
    clean ``FileNotFoundError`` (cold start)."""
    if tag is not None:
        if read_commit(directory, tag) is not None:
            ok, why = verify_commit(directory, tag, deep=True)
            if not ok:
                raise ValueError(
                    f"checkpoint {tag} fails its commit manifest ({why})"
                    " — torn or corrupt (missing/damaged shard data)")
        return tag
    condemned: set = set()
    while True:
        t = latest_tag(directory)
        if t is None:
            raise FileNotFoundError(f"No checkpoints in {directory}")
        if t in condemned:
            # discard_tag could not actually remove it (read-only
            # mirror, permissions) — refuse rather than spin forever
            raise ValueError(
                f"checkpoint {t} failed verification but could not be "
                "removed (read-only checkpoint directory?) — refusing "
                "to restore a corrupt checkpoint")
        if read_commit(directory, t) is None:
            return t  # legacy directory: no checksums to hold it to
        ok, why = verify_commit(directory, t, deep=True)
        if ok:
            return t
        _log.warning("discarding corrupt checkpoint", tag=t, reason=why,
                     directory=directory)
        train_metrics.record_ckpt_restore("corrupt_discarded")
        condemned.add(t)
        discard_tag(directory, t)


def restore_checkpoint(directory: str, template, tag: Any = None,
                       _record: bool = True):
    """Load ``ckpt_<tag>`` into the structure of ``template``.  With
    ``tag=None`` the newest *complete* checkpoint is selected (commit
    manifest verified; corrupt tags deleted and skipped)."""
    tag = _resolve_tag(directory, tag)
    path = os.path.join(directory, f"ckpt_{tag}.npz")
    data = np.load(path)
    leaves = [data[f"arr_{i}"] for i in range(len(data.files))]
    flat_np, treedef = jax.tree_util.tree_flatten_with_path(template)
    flat = [leaf for _, leaf in flat_np]
    tmpl_named = [(_path_name(p), tmpl) for p, tmpl in flat_np]
    saved_names = None
    manifest_path = os.path.join(directory, f"ckpt_{tag}.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            saved_names = json.load(f).get("names")
    names_usable = (saved_names is not None
                    and len(saved_names) == len(leaves))
    if (len(flat) != len(leaves)
            or (names_usable
                and saved_names != [n for n, _ in tmpl_named])):
        # name drift or structure evolution — same name/shape matcher
        # as restore_sharded (blind positional loading is unsafe even
        # at equal counts: lexicographic dict flattening flips leaf
        # order when auto-numbered names cross a digit boundary)
        if not names_usable:
            raise ValueError(
                f"Checkpoint has {len(leaves)} leaves, template has "
                f"{len(flat)} (and no usable name manifest to bridge)")
        pairs = _remap_by_name(tag, saved_names,
                               [np.shape(l) for l in leaves],
                               tmpl_named)
        leaves = [leaves[si] if si is not None else d
                  for si, d in pairs]
    for tmpl, loaded in zip(flat, leaves):
        if np.shape(tmpl) != loaded.shape:
            raise ValueError(
                f"Leaf shape mismatch: {np.shape(tmpl)} vs {loaded.shape}")
    if _record:
        train_metrics.record_ckpt_restore("ok")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def read_meta(directory: str, tag: Any = None) -> dict:
    tag = tag if tag is not None else latest_tag(directory)
    with open(os.path.join(directory, f"ckpt_{tag}.json")) as f:
        return json.load(f).get("meta", {})


# ------------------------------------------------------------- sharded ----

def _none_leaf(x):
    return x is None


def _flatten_none_aware(tree):
    """Flatten keeping structural ``None`` nodes AS leaves — save and
    restore must agree on leaf indices even for trees containing None
    (e.g. optax.masked / inject_hyperparams states)."""
    return jax.tree_util.tree_flatten(tree, is_leaf=_none_leaf)


def _leaf_names(tree):
    """Path-string per leaf (None-aware flatten, matching the sharded
    format's save-side manifest)."""
    flat = jax.tree_util.tree_flatten_with_path(tree, is_leaf=_none_leaf)[0]
    return [_path_name(path) for path, _ in flat]


# Structure-evolution escape hatch: a layer that ADDS a state leaf in a
# later version registers a restore default here, so checkpoints saved
# before the addition still load (restore matches leaves BY NAME against
# the manifest and fills registered absentees).  The layer owns the
# migration semantics — e.g. BatchNormalization fills its debias
# ``count`` with inf, which makes pre-existing moving stats behave
# exactly as they did when saved.
RESTORE_DEFAULTS: list = []


def register_restore_default(pattern: str, fill) -> None:
    """``pattern`` is a regex matched (re.search) against the leaf's
    path name; ``fill(template_leaf) -> array`` produces the value."""
    RESTORE_DEFAULTS.append((re.compile(pattern), fill))


def _fill_default(name, tmpl):
    for pat, fill in RESTORE_DEFAULTS:
        if pat.search(name):
            return np.asarray(fill(tmpl))
    return None


# Structure-evolution escape hatch #2: a layer RENAMED in a later
# version registers an old→new alias here (patterns run against the
# auto-number-STRIPPED saved path).  Applied only to leaves the primary
# name+shape matcher left unpaired, so a model that legitimately
# contains both names is never hijacked.  An optional guard predicate
# over (leftover saved stripped paths, unmatched template stripped
# paths) scopes an alias to the exact migration signature — a too-broad
# alias would turn the loud "structure changed" failure into a silent
# wrong-weights load.
def _component_in(names, component: str) -> bool:
    pat = re.compile(rf"(^|/){component}(/|$)")
    return any(pat.search(n) for n in names)


def _lm_pre_generate_signature(leftover_saved, unmatched_tmpl) -> bool:
    """Pre-generate() TransformerLM migration: the save carries BOTH
    auto-named embedding layers unpaired, and the template is missing
    BOTH of their current spellings.  A current model that merely uses
    an auto-named PositionalEmbedding (a live exported layer) direct-
    matches it, so its template has no unmatched pos_embed and the
    aliases stay inert."""
    return (_component_in(leftover_saved, "embedding")
            and _component_in(leftover_saved, "positionalembedding")
            and _component_in(unmatched_tmpl, "tok_embed")
            and _component_in(unmatched_tmpl, "pos_embed"))


RESTORE_RENAMES: list = [
    # TransformerLM builds before the generate() release auto-named the
    # two embedding layers; current builds use stable names
    # (models/textgeneration.py: tok_embed / pos_embed).
    (re.compile(r"(^|/)positionalembedding(/|$)"), r"\1pos_embed\2",
     _lm_pre_generate_signature),
    (re.compile(r"(^|/)embedding(/|$)"), r"\1tok_embed\2",
     _lm_pre_generate_signature),
]


def register_restore_rename(pattern: str, replacement: str,
                            guard=None) -> None:
    """``pattern``/``replacement`` rewrite an OLD stripped leaf path to
    its current spelling (re.sub semantics); optional
    ``guard(leftover_saved, unmatched_tmpl)`` activates the alias only
    when both sides carry the expected migration signature."""
    RESTORE_RENAMES.insert(0, (re.compile(pattern), replacement, guard))


def _apply_renames(stripped: str, active) -> str:
    # first matching pattern wins: a later alias must not re-rewrite the
    # TARGET of an earlier one (e.g. a user alias whose new spelling
    # itself contains an "embedding" path segment)
    for pat, repl in active:
        renamed = pat.sub(repl, stripped)
        if renamed != stripped:
            return renamed
    return stripped


def _remap_by_name(tag, saved_names, saved_shapes, tmpl_named):
    """The name/shape-aware leaf matcher shared by both restore formats.

    ``tmpl_named`` is [(name, template_leaf)]; ``saved_shapes`` aligns
    with ``saved_names`` (None for structural-None leaves).  Matching,
    per template leaf:

    1. its ordinal within its (auto-number-stripped name, shape) group,
       both sides ordered by NATURAL numeric-suffix sort — the only
       identity stable across builds (see the comment below);
    2. a registered RESTORE_DEFAULT (a leaf added after the save);
    3. otherwise fail loudly.

    Returns a list of (saved_index, default) pairs — at most one of
    each pair is non-None."""
    # Group BOTH sides by (auto-number-stripped name, shape) and pair
    # group members ordinally under NATURAL (numeric-suffix) sort.
    # Exact-name matching is deliberately NOT given precedence: an
    # auto-numbered name is not a stable identity across builds — two
    # builds whose counters overlap can give the same name to different
    # layers, and lexicographic manifest order flips at digit
    # boundaries, so only (stripped name, shape, ordinal-in-group) is
    # build-stable.  For stable user-assigned names (groups of one, or
    # consistently numbered like attn_0/attn_1) ordinal pairing reduces
    # to exact matching.
    pool: dict = {}
    for i, (n, sh) in enumerate(zip(saved_names, saved_shapes)):
        if sh is not None:
            pool.setdefault((_strip_auto_numbers(n), tuple(sh)),
                            []).append(i)
    for members in pool.values():
        members.sort(key=lambda i: _natural_key(saved_names[i]))
    tgroups: dict = {}
    for ti, (name, tmpl) in enumerate(tmpl_named):
        if tmpl is not None:
            tgroups.setdefault(
                (_strip_auto_numbers(name), tuple(np.shape(tmpl))),
                []).append(ti)
    assign: dict = {}
    for key, tpos in tgroups.items():
        tpos.sort(key=lambda ti: _natural_key(tmpl_named[ti][0]))
        for ti, si in zip(tpos, pool.get(key, [])):
            assign[ti] = si
    # second chance for RENAMED layers (RESTORE_RENAMES): run the alias
    # table over the stripped names of saved leaves the primary pass
    # left unconsumed, and pair them with still-unmatched template
    # leaves the same ordinal way.  Leftovers only, so a model that
    # contains both the old and the new name keeps its direct matches.
    consumed = set(assign.values())
    leftover_saved = {key[0] for key, members in pool.items()
                      if any(i not in consumed for i in members)}
    unmatched_tmpl = {key[0] for key, tpos in tgroups.items()
                      if any(ti not in assign for ti in tpos)}
    active = [r[:2] for r in RESTORE_RENAMES
              if len(r) < 3 or r[2] is None
              or r[2](leftover_saved, unmatched_tmpl)]
    alias_pool: dict = {}
    for (sname, shape), members in pool.items():
        rest = [i for i in members if i not in consumed]
        renamed = _apply_renames(sname, active)
        if rest and renamed != sname:
            alias_pool.setdefault((renamed, shape), []).extend(rest)
    for members in alias_pool.values():
        members.sort(key=lambda i: _natural_key(saved_names[i]))
    for key, tpos in tgroups.items():
        unmatched = [ti for ti in tpos if ti not in assign]
        for ti, si in zip(unmatched, alias_pool.get(key, [])):
            assign[ti] = si
    out = []
    for ti, (name, tmpl) in enumerate(tmpl_named):
        if tmpl is None:  # structural None carries no data
            out.append((None, None))
            continue
        si = assign.get(ti)
        if si is not None:
            out.append((si, None))
            continue
        d = _fill_default(name, tmpl)
        if d is None:
            raise ValueError(
                f"checkpoint {tag} has no leaf matching {name!r} "
                f"(shape {np.shape(tmpl)}) by stripped-name+shape, and "
                "no restore default is registered for it — model/"
                "optimizer structure changed since the save in a way "
                "restore cannot bridge")
        out.append((None, d))
    return out


def _strip_auto_numbers(name: str) -> str:
    """Drop the trailing ``_<n>`` auto-number from each path component —
    two builds of the same model differ only in these."""
    return "/".join(re.sub(r"_\d+$", "", part)
                    for part in name.split("/"))


def _natural_key(name: str):
    """Sort key ordering auto-numbered path components NUMERICALLY
    (construction order): dense_9 < dense_10 < dense_11, which
    lexicographic string order violates at digit boundaries."""
    key = []
    for part in name.split("/"):
        m = re.match(r"(.*?)_(\d+)$", part)
        if m:
            key.append((m.group(1), int(m.group(2))))
        else:
            key.append((part, -1))
    return key


# BatchNormalization's debias ``count`` leaf (added r5; the layer keeps
# user-assignable names so the match is on the leaf name alone —
# registered here rather than in the layer module to avoid a
# layers -> train import cycle).  Pre-existing moving stats restore as
# converged averages (count=inf => debias denominator 1): exactly the
# inference semantics they had when saved.
register_restore_default(
    r"(^|/)count$",
    lambda tmpl: np.full(np.shape(tmpl), np.inf, np.float32))


def _encode_index(index, shape):
    """Slice tuple (global coords) -> 'start:stop,start:stop,...'."""
    parts = []
    for sl, dim in zip(index, shape):
        parts.append(f"{sl.start or 0}:{dim if sl.stop is None else sl.stop}")
    return ",".join(parts)


def _decode_index(text):
    if not text:
        return ()
    return tuple(slice(int(a), int(b))
                 for a, b in (p.split(":") for p in text.split(",")))


def _host_shards(leaf):
    """Yield (index, np_array) for the unique (replica-0) device shards of
    ``leaf`` addressable from this process.  Plain host arrays yield one
    full-extent shard on process 0 only."""
    shape = np.shape(leaf)
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        for s in leaf.addressable_shards:
            if s.replica_id != 0:  # replicated copy — someone else saves it
                continue
            index = s.index if s.index else tuple(
                slice(0, d) for d in shape)
            yield index, np.asarray(s.data)
    elif jax.process_index() == 0:
        yield tuple(slice(0, d) for d in shape), np.asarray(leaf)


def _snapshot_shards(tree):
    """Synchronously copy this process's shards to host memory (so the
    training loop may donate/overwrite the device buffers immediately)."""
    flat = jax.tree_util.tree_flatten_with_path(tree, is_leaf=_none_leaf)[0]
    names = [_path_name(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    arrays = {}
    shapes, dtypes = [], []
    for i, leaf in enumerate(leaves):
        if leaf is None:  # structural None: keeps the index, stores nothing
            shapes.append(None)
            dtypes.append(None)
            continue
        shapes.append(list(np.shape(leaf)))
        dtypes.append(str(getattr(leaf, "dtype", np.asarray(leaf).dtype)))
        for index, data in _host_shards(leaf):
            arrays[f"{i}|{_encode_index(index, np.shape(leaf))}"] = data
    return names, shapes, dtypes, arrays


def _write_shards(directory: str, tag: Any, pid: int, n_processes: int,
                  names, shapes, dtypes, arrays,
                  meta: Optional[dict], overwrite: bool = True) -> str:
    """The single on-disk writer for the sharded format (used by both the
    sync and async paths).  Process 0 writes the manifest, which records
    ``n_processes`` so restore reads EXACTLY that shard-file set — stale
    files from an earlier save with a larger pod are ignored."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt_{tag}.shard-p{pid}.npz")
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists and overwrite=False "
                              "(reference setCheckpoint overWrite semantics)")
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    if pid == 0:
        manifest = {"format": "sharded", "tag": str(tag),
                    "meta": meta or {}, "n_processes": n_processes,
                    "names": names, "shapes": shapes, "dtypes": dtypes}
        with open(os.path.join(directory, f"ckpt_{tag}.json"), "w") as f:
            json.dump(manifest, f)
    train_metrics.record_ckpt_save("sharded")
    return path


def _commit_sharded(directory: str, tag: Any, n_processes: int,
                    wait_s: Optional[float] = None) -> bool:
    """Rank 0's pod-level commit: require EVERY process's shard file
    present, then write the commit manifest (atomic rename, the final
    step).  Presence == complete because shard writes are tmp+rename.
    The sync save path reaches here after a device barrier (the wait
    loop exits immediately); the async path has no barrier available on
    a writer thread, so this waits on the shared filesystem instead —
    on timeout the tag simply stays uncommitted (never restorable),
    which is the fail-safe outcome."""
    shard_files = [f"ckpt_{tag}.shard-p{p}.npz" for p in range(n_processes)]
    covered = shard_files + [f"ckpt_{tag}.json"]
    deadline = time.monotonic() + (_COMMIT_WAIT_S if wait_s is None
                                   else wait_s)
    while True:
        missing = [f for f in covered
                   if not os.path.exists(os.path.join(directory, f))]
        if not missing:
            break
        if time.monotonic() > deadline:
            _log.error("checkpoint commit timed out waiting for shards — "
                       "tag left uncommitted (will never be restored)",
                       tag=str(tag), missing=missing)
            return False
        time.sleep(0.05)
    _write_commit(directory, tag, covered, n_processes)
    # drill hook: a post-commit corruption is exactly what restore-side
    # checksum verification exists to catch
    faults.maybe_corrupt_shard(directory, tag)
    return True


def _pod_barrier(name: str):
    """Block until every pod process reaches this point (no-op
    single-process).  Must be called from the main thread by ALL
    processes."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def save_sharded(directory: str, tag: Any, tree, overwrite: bool = True,
                 meta: Optional[dict] = None) -> str:
    """Write this process's addressable shards of every leaf.  Every pod
    process calls this concurrently; process 0 additionally writes the
    manifest.  Replicated leaves are deduplicated via ``replica_id == 0``
    so each byte is stored exactly once across the pod.  Returns after ALL
    processes have written (pod barrier), so a restore anywhere on the pod
    immediately after is safe."""
    names, shapes, dtypes, arrays = _snapshot_shards(tree)
    wrote = False
    try:
        path = _write_shards(directory, tag, jax.process_index(),
                             jax.process_count(), names, shapes, dtypes,
                             arrays, meta, overwrite)
        wrote = True
    finally:
        # the barrier must run on EVERY process even when this one's
        # write raises (e.g. overwrite=False and the file exists) —
        # skipping it would leave the rest of the pod blocked forever
        _pod_barrier(f"zoo_ckpt_{tag}")
        try:
            # pod-level commit: all shards are durable past the barrier;
            # rank 0 writes the commit manifest as the final step, and a
            # second barrier keeps any process from restoring before the
            # tag is actually committed
            if wrote and jax.process_index() == 0:
                _commit_sharded(directory, tag, jax.process_count())
        finally:
            _pod_barrier(f"zoo_ckpt_commit_{tag}")
    return path


def async_save_sharded(directory: str, tag: Any, tree,
                       meta: Optional[dict] = None):
    """Sharded analog of ``async_save``: device→host copy happens now,
    file writes happen on a daemon thread.  NOTE: join via
    ``wait_pending`` (local) and, on a pod, a cross-process barrier before
    restoring — ``Trainer.fit`` does both when it returns."""
    names, shapes, dtypes, arrays = _snapshot_shards(tree)
    pid, nproc = jax.process_index(), jax.process_count()

    def _write_and_commit():
        _write_shards(directory, tag, pid, nproc, names, shapes, dtypes,
                      arrays, meta)
        if pid == 0:
            # no device barrier is available off the main thread; the
            # commit waits for the other processes' shard files on the
            # shared filesystem instead (atomic renames make presence
            # mean complete)
            _commit_sharded(directory, tag, nproc)

    t = threading.Thread(target=_write_and_commit, daemon=True)
    t.start()
    _PENDING.append((os.path.abspath(directory), t))
    return t


def restore_sharded(directory: str, template, tag: Any = None,
                    shardings=None):
    """Reassemble global leaves from every process's shard files and place
    them under ``shardings`` (a pytree of NamedSharding matching
    ``template``; None leaves — or ``shardings=None`` — return host numpy).

    Because the on-disk format is mesh-agnostic (global indices), a
    checkpoint saved under one mesh/strategy restores onto ANY other —
    the re-sharding story SURVEY §5 prescribes.  Requires all shard files
    to be visible (shared filesystem on a pod).

    With ``tag=None`` only COMPLETE checkpoints are candidates: a tag
    without a valid commit manifest is skipped, and one whose checksums
    fail at restore is deleted before falling back to the next newest
    complete tag (``FileNotFoundError`` when none survive — cold
    start)."""
    tag = _resolve_tag(directory, tag)
    # the manifest records how many processes wrote this save; reading
    # exactly that set ignores stale shard files from an older save of
    # the same tag under a larger pod
    manifest = {}
    manifest_path = os.path.join(directory, f"ckpt_{tag}.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    n_saved = manifest.get("n_processes")
    if n_saved is not None:
        shard_files = [f"ckpt_{tag}.shard-p{p}.npz" for p in range(n_saved)]
        missing = [f for f in shard_files
                   if not os.path.exists(os.path.join(directory, f))]
        if missing:
            raise ValueError(
                f"checkpoint {tag} was written by {n_saved} processes but "
                f"{missing} are absent (is the checkpoint directory "
                "shared across all pod processes?)")
    else:
        shard_files = sorted(
            f for f in os.listdir(directory)
            if re.match(rf"ckpt_{re.escape(str(tag))}\.shard-p\d+\.npz$",
                        f))
    if not shard_files:
        # fall back to the flat format for old checkpoints (then place
        # under the same target shardings); the tag is already verified,
        # and counting happens below
        tree = restore_checkpoint(directory, template, tag, _record=False)
        tree = _place_tree(tree, shardings)
        train_metrics.record_ckpt_restore("ok")
        return tree
    flat, treedef = _flatten_none_aware(template)
    shard_flat = ([None] * len(flat) if shardings is None
                  else _flatten_none_aware(shardings)[0])
    if len(shard_flat) != len(flat):
        raise ValueError(
            f"shardings tree has {len(shard_flat)} leaves, template has "
            f"{len(flat)} — structures must match")
    # leaf-index remap for structure evolution: when the manifest's
    # saved names differ from the template's (a layer added/moved a
    # state leaf since the save), match BY NAME; template leaves absent
    # from the save fill from RESTORE_DEFAULTS or fail loudly
    saved_names = manifest.get("names")
    tmpl_names = _leaf_names(template)
    defaults: dict = {}
    # identical names => identity mapping (the common resume).  ANY
    # name drift goes through the name/shape matcher: auto-numbered
    # names drift between two builds of the same model, and because
    # dict keys flatten lexicographically, crossing a digit boundary
    # (dense_99 -> dense_100) even flips leaf ORDER — blind positional
    # loading would put weights in the wrong layers (caught live as a
    # broadcast error, r5).
    if saved_names is not None and saved_names != tmpl_names:
        saved_shapes = (manifest.get("shapes")
                        or [None] * len(saved_names))
        pairs = _remap_by_name(tag, saved_names, saved_shapes,
                               list(zip(tmpl_names, flat)))
        remap = [si for si, _ in pairs]
        defaults = {ti: d for ti, (_, d) in enumerate(pairs)
                    if d is not None}
    else:
        remap = list(range(len(flat)))
    # index every entry key by leaf (npz members load lazily, so this
    # only reads the zip directories), then assemble + place ONE leaf at
    # a time — restore stays bounded by the largest leaf, not the whole
    # state (the same bounded-memory property save has)
    handles = [np.load(os.path.join(directory, f)) for f in shard_files]
    try:
        n_saved_leaves = (len(saved_names) if saved_names is not None
                          else len(flat))
        by_leaf: dict = {}
        for h in handles:
            for key in h.files:
                si, _, idx_text = key.partition("|")
                i = int(si)
                if i >= n_saved_leaves:
                    raise ValueError(
                        f"checkpoint {tag} has a leaf index {i} but "
                        f"records only {n_saved_leaves} leaves — shard "
                        "files from a different save mixed in?")
                by_leaf.setdefault(i, []).append((h, key, idx_text))
        placed = []
        for i, (tmpl, sh) in enumerate(zip(flat, shard_flat)):
            if tmpl is None:
                placed.append(None)
                continue
            if i in defaults:  # registered fill for a post-save leaf
                buf = defaults[i]
                if sh is None:
                    placed.append(buf)
                else:
                    placed.append(jax.make_array_from_callback(
                        np.shape(buf), sh, lambda idx, b=buf: b[idx]))
                continue
            entries = by_leaf.get(remap[i])
            if not entries:
                raise ValueError(
                    f"checkpoint {tag} is missing data for leaf {i} "
                    f"(shape {np.shape(tmpl)}) — incomplete shard set?")
            shape = np.shape(tmpl)
            buf = None
            filled = 0
            for h, key, idx_text in entries:
                piece = h[key]
                index = _decode_index(idx_text)
                if not index:  # scalar leaf
                    buf, filled = piece, 1
                    continue
                if buf is None:
                    buf = np.empty(shape,
                                   getattr(tmpl, "dtype", piece.dtype))
                buf[index] = piece
                filled += piece.size
            want = int(np.prod(shape)) if shape else 1
            if filled < want:
                raise ValueError(
                    f"checkpoint {tag} leaf {i} only has {filled}/{want} "
                    "elements — missing shard files (is the checkpoint "
                    "directory shared across all pod processes?)")
            if np.shape(buf) != shape:
                raise ValueError(
                    f"Leaf shape mismatch: {shape} vs {np.shape(buf)}")
            if sh is None:
                placed.append(buf)
            else:
                placed.append(jax.make_array_from_callback(
                    shape, sh, lambda idx, b=buf: b[idx]))
            del buf  # free before assembling the next leaf
    finally:
        for h in handles:
            h.close()
    train_metrics.record_ckpt_restore("ok")
    return jax.tree_util.tree_unflatten(treedef, placed)


def _place_tree(tree, shardings):
    """Place host leaves under target shardings (None leaves / None tree
    stay on host).  ``make_array_from_callback`` hands each device only
    its own slice, so a pod-wide array never materializes per-device
    copies of the full leaf."""
    if shardings is None:
        return tree
    # BOTH trees flatten None-aware so structural Nones cannot shift the
    # (leaf, sharding) pairing
    flat, treedef = _flatten_none_aware(tree)
    shard_flat = _flatten_none_aware(shardings)[0]
    if len(flat) != len(shard_flat):
        raise ValueError(
            f"shardings tree has {len(shard_flat)} leaves, value tree has "
            f"{len(flat)} — structures must match")
    placed = []
    for buf, sh in zip(flat, shard_flat):
        if sh is None or buf is None:
            placed.append(buf)
        else:
            buf = np.asarray(buf)
            placed.append(jax.make_array_from_callback(
                np.shape(buf), sh, lambda idx, b=buf: b[idx]))
    return jax.tree_util.tree_unflatten(treedef, placed)
