from .trainer import Trainer, TrainState
from .triggers import (Trigger, EveryEpoch, MaxEpoch, MaxIteration,
                       SeveralIteration, MinLoss)
from .summary import TrainSummary, ValidationSummary, SummaryWriter
from . import checkpoint
