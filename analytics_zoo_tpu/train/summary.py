"""Training/validation summaries (TensorBoard-compatible scalars).

Parity surface: BigDL TrainSummary/ValidationSummary wired via
``setTensorBoard(logDir, appName)`` (reference: Topology.scala:157-175,
NNEstimator.scala:218-253).  Scalars (Loss, LearningRate, Throughput,
validation metrics) are written as native TensorBoard event files — a
minimal, dependency-free tfevents writer (record framing + masked CRC32c per
the TFRecord spec) — plus a human/machine-friendly ``scalars.jsonl``.
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import Dict, List, Optional, Tuple


def _crc32c(data: bytes) -> int:
    """Software CRC32C (Castagnoli), table-driven."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC_TABLE = None


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _scalar_event_proto(step: int, tag: str, value: float,
                        wall_time: float) -> bytes:
    """Hand-encode an Event{wall_time, step, summary{value{tag,
    simple_value}}} protobuf (schema: tensorflow/core/util/event.proto)."""
    tag_b = tag.encode("utf-8")
    sv = _tag(1, 2) + _varint(len(tag_b)) + tag_b  # Summary.Value.tag = 1
    sv += _tag(2, 5) + struct.pack("<f", value)    # simple_value = 2
    summary = _tag(1, 2) + _varint(len(sv)) + sv   # Summary.value = 1
    event = _tag(1, 1) + struct.pack("<d", wall_time)  # Event.wall_time = 1
    event += _tag(2, 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)  # Event.step = 2
    event += _tag(5, 2) + _varint(len(summary)) + summary     # summary = 5
    return event


class SummaryWriter:
    """Append-only tfevents + jsonl scalar writer."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.zoo_tpu"
        self._events_path = os.path.join(log_dir, fname)
        self._events = open(self._events_path, "ab")
        self._jsonl = open(os.path.join(log_dir, "scalars.jsonl"), "a")
        self._history: Dict[str, List[Tuple[int, float]]] = {}
        self._triggers: Dict[str, object] = {}

    def set_summary_trigger(self, tag: str, trigger) -> "SummaryWriter":
        """Throttle how often a tag is recorded — parity with BigDL
        ``TrainSummary.setSummaryTrigger`` (used by the reference
        recommendation notebooks: ``set_summary_trigger("Loss",
        SeveralIteration(1))``).  ``trigger`` is any
        ``analytics_zoo_tpu.train.triggers.Trigger``; it gates
        ``add_scalar`` for that tag, whatever the tag is."""
        self._triggers[tag] = trigger
        return self

    def should_log(self, tag: str, step: int) -> bool:
        trig = self._triggers.get(tag)
        if trig is None:
            return True
        return bool(trig({"iteration": int(step)}))

    def add_scalar(self, tag: str, value: float, step: int):
        if not self.should_log(tag, step):
            return
        wall = time.time()
        record = _scalar_event_proto(step, tag, float(value), wall)
        header = struct.pack("<Q", len(record))
        self._events.write(header)
        self._events.write(struct.pack("<I", _masked_crc(header)))
        self._events.write(record)
        self._events.write(struct.pack("<I", _masked_crc(record)))
        self._jsonl.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step),
             "wall_time": wall}) + "\n")
        self._history.setdefault(tag, []).append((int(step), float(value)))

    def flush(self):
        self._events.flush()
        self._jsonl.flush()

    def close(self):
        self._events.close()
        self._jsonl.close()

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """Mirror of the reference's TrainSummary.readScalar."""
        return list(self._history.get(tag, []))


def read_scalars(log_dir: str, app_name: str, tag: str,
                 split: str = "train") -> List[Tuple[int, float]]:
    """Read a PAST run's scalars back from disk (the reference's
    TrainSummary.readScalar works on saved logs; the in-memory
    ``read_scalar`` only covers the live writer).  Reads the jsonl
    sidecar, so no TF dependency is needed to plot a finished run."""
    path = os.path.join(log_dir, app_name, split, "scalars.jsonl")
    out: List[Tuple[int, float]] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a crashed writer
            if rec.get("tag") == tag:
                out.append((int(rec["step"]), float(rec["value"])))
    return out


class TrainSummary(SummaryWriter):
    """Scalars: Loss, LearningRate, Throughput (parity with BigDL)."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(os.path.join(log_dir, app_name, "train"))
        self.app_name = app_name


class ValidationSummary(SummaryWriter):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(os.path.join(log_dir, app_name, "validation"))
        self.app_name = app_name
