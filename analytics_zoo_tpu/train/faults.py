"""Fault-tolerance env contracts: resume, heartbeat, and fault injection.

This module is the single home of the process-level contracts the
supervising launcher (``launcher.py``) and the training loop
(``train/trainer.py``) agree on — BigDL-style coarse-grained recovery
(reference: docs/docs/wp-bigdl.md failure story; SURVEY §5) needs the
worker and its supervisor to speak the same env-var protocol:

``ZOO_RESUME``
    Set by the supervisor on every *relaunch* of a crashed pod.  A
    ``Trainer.fit`` with a ``set_checkpoint`` directory restores
    params/opt_state/step/epoch from the newest **complete** checkpoint
    and fast-forwards the data pipeline to the restored position.  No
    complete checkpoint → clean cold start (a crash may cost lost
    steps, never a wrong or torn restore).
``ZOO_RESTART_COUNT``
    Informational: which relaunch this incarnation is (1-based).
``ZOO_HEARTBEAT_FILE``
    Per-worker liveness file.  The training loop touches it (throttled)
    every step; the supervisor's watchdog SIGKILLs + relaunches the pod
    when it goes stale past ``--watchdog-sec`` — the hang-detection
    half of recovery (a worker stuck in a dead collective never exits
    on its own).  Deliberately touched from the *training* thread, not
    a daemon thread: a heartbeat thread would keep beating under a
    deadlocked main thread, which is exactly the failure the watchdog
    exists to catch.
``ZOO_CKPT_SYNC``
    Makes iteration-trigger checkpoints synchronous (``save_sharded``
    instead of ``async_save_sharded``) so a fault injected at step k
    deterministically finds every earlier checkpoint committed — used
    by the fault drill; production keeps the async default.

Fault-injection hooks (test/drill only — all are one-shot per pod:
they disarm when ``ZOO_RESUME`` is set, so a restarted pod doesn't
re-crash at the same step forever):

``ZOO_FAULT_CRASH_STEP`` / ``ZOO_FAULT_CRASH_RANK`` (default 1)
    SIGKILL this process after completing the given step.
``ZOO_FAULT_HANG_STEP`` / ``ZOO_FAULT_HANG_RANK`` (default 1)
    Hang (stop heartbeating) after the given step — watchdog fodder.
``ZOO_FAULT_CORRUPT_TAG``
    After rank 0 durably commits this checkpoint tag, flip bytes in its
    own shard file — the commit manifest's checksums then convict the
    tag at restore time (torn-restore drill).

Rank here is the launcher's ``ZOO_TPU_PROCESS_ID`` (falling back to
``JAX_PROCESS_ID``), read from env so this module never imports jax.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .. import envcontract

ENV_RESUME = "ZOO_RESUME"
ENV_RESTART_COUNT = "ZOO_RESTART_COUNT"
ENV_HEARTBEAT = "ZOO_HEARTBEAT_FILE"
ENV_CKPT_SYNC = "ZOO_CKPT_SYNC"
ENV_CRASH_STEP = "ZOO_FAULT_CRASH_STEP"
ENV_CRASH_RANK = "ZOO_FAULT_CRASH_RANK"
ENV_HANG_STEP = "ZOO_FAULT_HANG_STEP"
ENV_HANG_RANK = "ZOO_FAULT_HANG_RANK"
ENV_CORRUPT_TAG = "ZOO_FAULT_CORRUPT_TAG"

_HEARTBEAT_MIN_INTERVAL_S = 0.5

# refreshed from env by refresh(); cached so the per-step hooks cost one
# attribute load + branch when nothing is armed
_hb_path: Optional[str] = None
_hb_last: float = 0.0
_crash_step: Optional[int] = None
_hang_step: Optional[int] = None


def _rank() -> int:
    return int(envcontract.env_str("ZOO_TPU_PROCESS_ID")
               or os.environ.get("JAX_PROCESS_ID") or 0)


def resume_requested() -> bool:
    return envcontract.env_flag(ENV_RESUME)


def sync_checkpoints() -> bool:
    return envcontract.env_flag(ENV_CKPT_SYNC)


def refresh() -> None:
    """Re-read the env contract (``Trainer.fit`` calls this at entry so
    a supervisor-provided environment — or a test's monkeypatch — takes
    effect without import-order coupling)."""
    global _hb_path, _crash_step, _hang_step
    _hb_path = envcontract.env_str(ENV_HEARTBEAT)
    _crash_step = None
    _hang_step = None
    # the structured logger stamps rank/incarnation from the same env
    # contract; re-read it alongside (jax-free import)
    from ..observability import log as _log
    _log.refresh_identity()
    if resume_requested():
        return  # fault hooks are one-shot: disarmed on a resumed pod
    rank = _rank()
    step = envcontract.env_str(ENV_CRASH_STEP)
    if step and rank == envcontract.env_int(ENV_CRASH_RANK, 1):
        _crash_step = int(step)
    step = envcontract.env_str(ENV_HANG_STEP)
    if step and rank == envcontract.env_int(ENV_HANG_RANK, 1):
        _hang_step = int(step)


def heartbeat() -> None:
    """Touch the supervisor's liveness file (throttled; no-op unless the
    launcher provided ``ZOO_HEARTBEAT_FILE``)."""
    global _hb_last
    if _hb_path is None:
        return
    now = time.monotonic()
    if now - _hb_last < _HEARTBEAT_MIN_INTERVAL_S:
        return
    _hb_last = now
    try:
        with open(_hb_path, "a"):
            os.utime(_hb_path, None)
    except OSError:
        pass  # liveness is best-effort telemetry; never fail training


def maybe_fault(step: int) -> None:
    """Injected crash/hang at the given completed step (drill hook)."""
    if _crash_step is not None and step == _crash_step:
        import signal
        # SIGKILL self: the hardest failure mode the supervisor must
        # handle — no atexit, no flushes, a torn in-flight checkpoint
        os.kill(os.getpid(), signal.SIGKILL)
    if _hang_step is not None and step == _hang_step:
        while True:  # stop heartbeating; only the watchdog ends this
            time.sleep(1.0)


def maybe_corrupt_shard(directory: str, tag) -> None:
    """Post-commit byte-flip of rank 0's own shard file for ``tag``
    (drill hook).  MUST only be called after the commit manifest is
    durable: corrupting before the digest would bake the bad bytes into
    the checksums and turn a detectable torn restore into a silently
    wrong one."""
    if resume_requested():
        return
    want = envcontract.env_str(ENV_CORRUPT_TAG)
    if not want or str(tag) != want:
        return
    path = os.path.join(directory, f"ckpt_{tag}.shard-p0.npz")
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]) if byte else b"\xff")
    except OSError:
        pass
