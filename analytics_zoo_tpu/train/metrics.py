"""Training-side metric families: restarts and checkpoint save/restore.

Process-local labeled counters (the supervisor and each worker count
their own process's events) exposed as Prometheus families through the
same :class:`~..observability.metrics.Family` exposition the serving
stack uses — wire them into any registry with::

    mreg.register_collector(train_families)

Families:

``zoo_train_steps_total``
    Worker-side: completed ``Trainer.fit`` steps.  The pod
    aggregator's join/straggler key: per-rank series sum to the pod
    total in the aggregated scrape (observability/aggregate.py).
``zoo_train_restarts_total{reason}``
    Supervisor-side: pod relaunches, by reason (``exit`` — a worker
    exited nonzero; ``watchdog`` — a heartbeat went stale and the
    worker was SIGKILLed; ``port`` — the coordinator port race, retried
    with a fresh port without consuming the restart budget).
``zoo_ckpt_saves_total{format}``
    Worker-side: checkpoint writes, by on-disk format
    (``flat``/``sharded``).
``zoo_ckpt_commits_total``
    Worker-side (rank 0): commit manifests durably written.
``zoo_ckpt_restores_total{outcome}``
    Worker-side: ``ok`` — a verified restore; ``corrupt_discarded`` — a
    tag failed its commit checksums and was deleted before falling back;
    ``cold_start`` — resume was requested but no complete checkpoint
    existed.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from ..observability.metrics import Family

_lock = threading.Lock()
_restarts: Dict[str, int] = {}
_saves: Dict[str, int] = {}
_restores: Dict[str, int] = {}
_commits: int = 0
_steps: int = 0


def record_restart(reason: str) -> None:
    with _lock:
        _restarts[reason] = _restarts.get(reason, 0) + 1


def record_step() -> None:
    """One completed training step (worker-side, per ``Trainer.fit``
    iteration).  The pod aggregator's straggler view and its
    sum-to-pod-total gate both key on this counter."""
    global _steps
    with _lock:
        _steps += 1


def record_ckpt_save(fmt: str) -> None:
    with _lock:
        _saves[fmt] = _saves.get(fmt, 0) + 1


def record_ckpt_commit() -> None:
    global _commits
    with _lock:
        _commits += 1


def record_ckpt_restore(outcome: str) -> None:
    with _lock:
        _restores[outcome] = _restores.get(outcome, 0) + 1


def snapshot() -> Dict[str, Dict[str, int]]:
    with _lock:
        return {"restarts": dict(_restarts), "ckpt_saves": dict(_saves),
                "ckpt_commits": _commits, "ckpt_restores": dict(_restores),
                "steps": _steps}


def reset() -> None:
    """Test isolation hook."""
    global _commits, _steps
    with _lock:
        _restarts.clear()
        _saves.clear()
        _restores.clear()
        _commits = 0
        _steps = 0


def train_families() -> List[Family]:
    """Current counters as exposition families (a registry collector)."""
    with _lock:
        fams = []
        if _steps:
            fams.append(Family(
                "counter", "zoo_train_steps_total",
                "Completed training steps in this process",
                [({}, _steps)]))
        if _restarts:
            fams.append(Family(
                "counter", "zoo_train_restarts_total",
                "Supervised pod relaunches by reason",
                [({"reason": r}, v) for r, v in sorted(_restarts.items())]))
        if _saves:
            fams.append(Family(
                "counter", "zoo_ckpt_saves_total",
                "Checkpoint writes by on-disk format",
                [({"format": f}, v) for f, v in sorted(_saves.items())]))
        if _commits:
            fams.append(Family(
                "counter", "zoo_ckpt_commits_total",
                "Checkpoint commit manifests durably written",
                [({}, _commits)]))
        if _restores:
            fams.append(Family(
                "counter", "zoo_ckpt_restores_total",
                "Checkpoint restore attempts by outcome",
                [({"outcome": o}, v) for o, v in sorted(_restores.items())]))
        return fams
