"""Trigger predicates: when to stop, checkpoint, or validate.

Parity surface: BigDL ``Trigger`` objects consumed by the reference
(everyEpoch, maxEpoch, maxIteration, severalIteration — used at
Topology.scala:83-87,268-271 and NNEstimator.scala:294-307).  A Trigger is a
pure predicate over the training record {epoch, iteration, epoch_finished}.
"""

from __future__ import annotations


class Trigger:
    def __call__(self, record: dict) -> bool:
        raise NotImplementedError

    # -- factories matching the reference's naming --
    @staticmethod
    def every_epoch():
        return EveryEpoch()

    @staticmethod
    def max_epoch(n):
        return MaxEpoch(n)

    @staticmethod
    def max_iteration(n):
        return MaxIteration(n)

    @staticmethod
    def several_iteration(n):
        return SeveralIteration(n)


class EveryEpoch(Trigger):
    def __call__(self, record):
        return bool(record.get("epoch_finished", False))


class MaxEpoch(Trigger):
    def __init__(self, n):
        self.n = int(n)

    def __call__(self, record):
        return record.get("epoch", 0) >= self.n


class MaxIteration(Trigger):
    def __init__(self, n):
        self.n = int(n)

    def __call__(self, record):
        return record.get("iteration", 0) >= self.n


class SeveralIteration(Trigger):
    def __init__(self, n):
        self.n = int(n)

    def __call__(self, record):
        it = record.get("iteration", 0)
        return it > 0 and it % self.n == 0


class MinLoss(Trigger):
    def __init__(self, min_loss):
        self.min_loss = float(min_loss)

    def __call__(self, record):
        return record.get("loss", float("inf")) <= self.min_loss


class And(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, record):
        return all(t(record) for t in self.triggers)


class Or(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, record):
        return any(t(record) for t in self.triggers)
