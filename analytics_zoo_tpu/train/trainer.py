"""Trainer: the compiled-SPMD training engine.

This single component replaces the reference's BigDL Optimizer /
DistriOptimizer machinery — the per-iteration "2 Spark jobs" ("model
forward-backward" then "parameter synchronization" via shuffle+broadcast
AllReduce, reference: docs/docs/wp-bigdl.md:113-160, driven from
Topology.scala:281, NNEstimator.scala:399, net.py:398-424).  Under jit the
whole iteration is ONE XLA computation: grad → (XLA-inserted psum over ICI
when the batch axis is sharded) → optax update, with the optimizer step
sharded alongside the params.

Semantics preserved from the reference:
* incremental fit — successive ``fit`` calls continue epoch counting
  (Topology.scala:284-297 reflective epoch bookkeeping);
* Trigger-driven validation / checkpoint / termination;
* TrainSummary scalars Loss / LearningRate / Throughput;
* gradient clipping composed into the optimizer chain.
"""

from __future__ import annotations

import itertools
import os
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import optax

from .. import envcontract
from ..common.utils import pad_leading
from ..data.dataset import (Dataset, check_batch_divisibility,
                            prefetch_iterator, shard_batch)
from ..observability import flightrec
from ..observability import trace as trace_lib
from ..parallel import distributed as dist_lib
from ..parallel import mesh as mesh_lib
from ..parallel import sharding as sharding_lib
from . import faults
from . import metrics as train_metrics
from . import stepprof
from . import triggers as trigger_lib
from .checkpoint import async_save_sharded, save_sharded
from .checkpoint import wait_pending as checkpoint_lib_wait_pending
from .summary import TrainSummary, ValidationSummary


# zero-pad the trailing partial batch of evaluate/predict to keep one
# compiled shape (shared helper: common/utils.py)
_pad_tail = pad_leading


class TrainState:
    """Mutable host-side holder of the on-device training pytrees."""

    def __init__(self, params, model_state, opt_state, step=0, epoch=0,
                 rng=None):
        self.params = params
        self.model_state = model_state
        self.opt_state = opt_state
        self.step = step
        self.epoch = epoch
        self.rng = rng

    def as_tree(self):
        return {"params": self.params, "model_state": self.model_state,
                "opt_state": self.opt_state}

    def load_tree(self, tree):
        self.params = tree["params"]
        self.model_state = tree["model_state"]
        self.opt_state = tree["opt_state"]


def _collect_aux(state) -> Any:
    """Differentiable auxiliary penalties that layers surface in their
    state under the reserved key ``aux_loss`` (SwitchMoE router
    balancing, W_regularizer penalties — already scaled by the layer).
    Training sums them into the loss INSIDE the grad closure so the
    penalty actually reaches the parameters; evaluate includes them so
    train and validation losses stay comparable (Keras semantics).
    Traverses RECURSIVELY: nested models (a Sequential added into
    another Sequential) nest their state one level per container."""
    total = 0.0
    if isinstance(state, dict):
        for key, sub in state.items():
            if key == "aux_loss":
                total = total + sub
            else:
                total = total + _collect_aux(sub)
    return total


def build_train_step(model, loss_fn, optimizer, compute_dtype=None,
                     jit: bool = True, donate: bool = True,
                     accum_steps: int = 1, in_shardings=None,
                     out_shardings=None):
    """THE training iteration: grad → (XLA-inserted psum when the batch is
    sharded) → optax update, with optional bf16 mixed precision (bf16
    compute/activations, f32 master weights; grads return f32 through the
    cast's transpose so the optax update — moments included — runs in
    f32) and optional gradient accumulation.  Single source of truth —
    the Trainer, bench.py and the driver dry run all compile this same
    function.

    ``accum_steps > 1``: ``x``/``y`` carry a LEADING microbatch axis
    ``(accum, micro, ...)`` and the step runs a ``lax.scan`` over it
    inside the ONE compiled program — gradients are accumulated in the
    master dtype and averaged (mean-of-means equals the full-batch mean
    for equal microbatches), the loss is the mean of microbatch losses,
    and microbatch ``i`` draws ``fold_in(rng, i)`` so the per-step
    ``fold_in(rng, step)`` determinism contract extends one level down.
    ``accum_steps == 1`` is byte-for-byte the historical single-shot
    step (no scan, rng consumed unsplit) so existing bit-exactness pins
    keep holding.

    ``in_shardings`` / ``out_shardings`` are forwarded to ``jax.jit`` —
    the sharded train-state layout (params + ZeRO optimizer state +
    batch) compiles in one pass with the whole state donated; ``None``
    entries let jax infer from the arguments (the replicated-batch
    fallback path stays compilable).

    Signature of the returned step:
        (params, model_state, opt_state, rng, x, y)
            -> (params, model_state, opt_state, loss)
    """
    cast = compute_dtype
    collect_aux = _collect_aux
    accum = max(int(accum_steps), 1)

    def compute_loss(p, mstate, step_rng, x, y):
        xin, p_in = x, p
        if cast is not None:
            castf = lambda a: (a.astype(cast) if jnp.issubdtype(
                a.dtype, jnp.floating) else a)
            xin = jax.tree_util.tree_map(castf, xin)
            p_in = jax.tree_util.tree_map(castf, p_in)
        y_pred, new_state = model.apply(
            p_in, mstate, xin, training=True, rng=step_rng)
        per_sample = loss_fn(y, y_pred.astype(jnp.float32)
                             if cast is not None else y_pred)
        loss = jnp.mean(per_sample) + collect_aux(new_state)
        return loss, new_state

    def train_step(params, model_state, opt_state, rng, x, y):
        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)
        if accum == 1:
            (loss, new_state), grads = grad_fn(params, model_state, rng,
                                               x, y)
        else:
            def micro_step(carry, inp):
                g_acc, loss_acc, mstate = carry
                i, xi, yi = inp
                (mloss, mstate), g = grad_fn(
                    params, mstate, jax.random.fold_in(rng, i), xi, yi)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                return (g_acc, loss_acc + mloss, mstate), None

            # accumulate in the MASTER dtype (grads already left the
            # bf16 region through the cast's transpose)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            (g_sum, loss_sum, new_state), _ = jax.lax.scan(
                micro_step,
                (zeros, jnp.zeros((), jnp.float32), model_state),
                (jnp.arange(accum), x, y))
            inv = 1.0 / accum
            grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
            loss = loss_sum * inv
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state, new_opt_state, loss

    if not jit:
        return train_step
    kwargs = {}
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    return jax.jit(train_step, donate_argnums=(0, 1, 2) if donate else (),
                   **kwargs)


#: env-contract knobs (declared in envcontract.VARS): deployment-wide
#: defaults for the sharding strategy / accumulation factor / compute
#: dtype — explicit constructor arguments always win
ENV_STRATEGY = "ZOO_TRAIN_STRATEGY"
ENV_ACCUM = "ZOO_TRAIN_ACCUM"
ENV_DTYPE = "ZOO_TRAIN_DTYPE"


def _dtype_from_env():
    """Resolve ``ZOO_TRAIN_DTYPE`` into a compute dtype (None = full
    f32).  An operator typo degrades to full precision with a warning —
    the env contract's "never crash a worker at import" rule."""
    name = (envcontract.env_str(ENV_DTYPE) or "").strip().lower()
    if not name:
        return None
    if name in ("bf16", "bfloat16"):
        return jnp.bfloat16
    if name in ("f16", "fp16", "float16"):
        return jnp.float16
    if name not in ("f32", "fp32", "float32"):
        from ..observability.log import get_logger
        get_logger("analytics_zoo_tpu.train").warning(
            "unknown ZOO_TRAIN_DTYPE — training in full f32", value=name)
    return None


class Trainer:
    def __init__(self, model, loss_fn: Callable, optimizer,
                 metrics: Sequence = (), mesh=None,
                 strategy: Optional[str] = None, seed: int = 0,
                 compute_dtype=None, accum_steps: Optional[int] = None,
                 tp_rules: Optional[Dict[str, int]] = None):
        """``model`` is any Layer (usually a GraphModule); ``loss_fn`` maps
        (y_true, y_pred) -> per-sample loss; ``optimizer`` is an optax
        transformation.

        ``strategy`` names the parameter/optimizer sharding plan
        (``parallel/sharding.py`` rule tables: replicate | fsdp | tp |
        fsdp_tp); ``tp_rules`` maps param-path regexes to the axis index
        sharded over ``tensor``.  ``accum_steps`` > 1 splits every global
        batch into that many microbatches scanned inside the one
        compiled step.  ``compute_dtype=jnp.bfloat16`` enables mixed
        precision (bf16 compute, f32 master weights + moments).  Each of
        strategy / accum_steps / compute_dtype falls back to its env
        knob (ZOO_TRAIN_STRATEGY / ZOO_TRAIN_ACCUM / ZOO_TRAIN_DTYPE)
        when not given."""
        self.model = model
        self.loss_fn = loss_fn
        # the optimizer actually stepped is the base masked by the
        # model's layer.trainable flags (freeze/unfreeze support)
        self._base_optimizer = optimizer
        self.optimizer = self._mask_from_flags(optimizer)
        self.metrics = list(metrics)
        self.mesh = mesh or mesh_lib.get_default_mesh()
        self.strategy = strategy or envcontract.env_str(
            ENV_STRATEGY, "replicate")
        self.tp_rules = dict(tp_rules) if tp_rules else None
        self.accum_steps = max(int(accum_steps) if accum_steps is not None
                               else envcontract.env_int(ENV_ACCUM, 1), 1)
        self.seed = seed
        self.compute_dtype = (compute_dtype if compute_dtype is not None
                              else _dtype_from_env())
        self.state: Optional[TrainState] = None
        self.train_summary: Optional[TrainSummary] = None
        self.val_summary: Optional[ValidationSummary] = None
        self._train_step = None
        self._eval_step = None
        self._eval_step_overrides: Dict[str, Any] = {}
        self._predict_step = None
        self._param_shardings = None
        self._opt_shardings = None
        self._batch_sharding = mesh_lib.data_sharding(self.mesh)
        # microbatched layout (accum, micro, ...): the data axes move to
        # dim 1, the scanned accumulation axis stays unsharded
        self._microbatch_sharding = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(
                None, *self._batch_sharding.spec))
        self._repl_sharding = mesh_lib.replicated(self.mesh)

    # ---- freeze support --------------------------------------------
    def _frozen_names(self) -> set:
        return {l.name for l in getattr(self.model, "layers", [])
                if not getattr(l, "trainable", True)}

    def _mask_from_flags(self, base):
        """Wrap ``base`` so layers with ``trainable=False`` receive
        EXACTLY zero updates, with a state structure that is INVARIANT
        under freeze/unfreeze: ``base``'s statistics always cover the
        full parameter tree, and the frozen set lives only in the update
        closure.  Toggling flags therefore never re-initializes
        optimizer state — still-training layers keep their momentum /
        Adam moments exactly, matching the reference's freeze
        (scaleW/scaleB=0, which never touches OptimMethod state;
        NetUtils.scala:216-277).

        Both the gradients entering and the updates leaving ``base`` are
        zeroed for frozen layers: zeroing the gradients keeps frozen
        layers' moments from absorbing gradient signal while frozen
        (they decay toward zero, equivalent to a fresh start on
        unfreeze); zeroing the updates guarantees exactly-zero movement
        even under stateful optimizers whose update is nonzero at zero
        gradient (momentum, Adam bias correction)."""
        frozen = frozenset(self._frozen_names())

        def _zero_frozen(tree):
            if not frozen:
                return tree
            return {k: (jax.tree_util.tree_map(jnp.zeros_like, sub)
                        if k in frozen else sub)
                    for k, sub in tree.items()}

        def update(grads, state, params=None):
            updates, new_state = base.update(_zero_frozen(grads), state,
                                             params)
            return _zero_frozen(updates), new_state

        from ..pipeline.api.keras.optimizers import ZooOptimizer
        return ZooOptimizer(base.init, update,
                            lr_fn=getattr(base, "lr_fn", None))

    def invalidate_compiled(self):
        """Drop the compiled step functions (they re-trace lazily) —
        TrainState (weights, optimizer state, epoch/step counters)
        survives."""
        self._train_step = None
        self._eval_step = None
        self._eval_step_overrides = {}
        self._predict_step = None

    def refresh_optimizer(self):
        """Re-derive the optimizer mask from the model's current
        trainable flags.  Optimizer STATISTICS are untouched — the mask
        wrapper's state structure is invariant under freeze/unfreeze
        (``_mask_from_flags``), so still-training layers keep their
        moments bit-for-bit and freshly-frozen weights cannot move on
        stale momentum (their updates are hard-zeroed)."""
        self.optimizer = self._mask_from_flags(self._base_optimizer)
        self.invalidate_compiled()

    # ------------------------------------------------------------------
    def ensure_initialized(self):
        if self.state is not None:
            return
        rng = jax.random.PRNGKey(self.seed)
        init_rng, loop_rng = jax.random.split(rng)
        params, model_state = self.model.init(
            init_rng, getattr(self.model, "batch_input_shape", None))
        # place according to strategy; XLA keeps them there across steps.
        # The optimizer state is initialized from the PLACED params so its
        # moment buffers inherit the same shardings (fsdp shards optimizer
        # state alongside params, ZeRO-style) — init-before-placement
        # would pin momentum to one device and conflict after a restore.
        self._param_shardings = sharding_lib.shard_params(
            params, self.mesh, self.strategy, tp_rules=self.tp_rules)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, s), params, self._param_shardings)
        model_state = jax.device_put(model_state, self._repl_sharding)
        opt_state = self.optimizer.init(params)
        self.state = TrainState(params, model_state, opt_state,
                                rng=loop_rng)

    def adopt_weights(self, params, model_state=None):
        """Replace weights with an externally provided pytree, re-placed
        under this trainer's shardings — used when compile() supersedes an
        inference-only trainer so pre-loaded weights survive.

        Shardings come from ``jax.eval_shape`` (abstract init) so no
        throwaway random initialization is materialized.  Raises
        ValueError when the provided tree doesn't match the model's
        parameter structure/shapes (e.g. the architecture changed since
        the weights were produced)."""
        rng = jax.random.PRNGKey(self.seed)
        init_rng, loop_rng = jax.random.split(rng)
        abs_params, abs_state = jax.eval_shape(
            lambda r: self.model.init(
                r, getattr(self.model, "batch_input_shape", None)),
            init_rng)
        same_struct = (jax.tree_util.tree_structure(params)
                       == jax.tree_util.tree_structure(abs_params))
        if not same_struct or any(
                tuple(np.shape(p)) != tuple(a.shape)
                for p, a in zip(jax.tree_util.tree_leaves(params),
                                jax.tree_util.tree_leaves(abs_params))):
            raise ValueError(
                "adopted weights do not match the model's parameter "
                "structure (did the architecture change?)")
        self._param_shardings = sharding_lib.shard_params(
            abs_params, self.mesh, self.strategy, tp_rules=self.tp_rules)
        placed = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, s), params,
            self._param_shardings)
        if model_state is None:
            if jax.tree_util.tree_leaves(abs_state):
                # stateful model with no adopted state: materialize one
                _, model_state = self.model.init(
                    init_rng, getattr(self.model, "batch_input_shape",
                                      None))
            else:
                model_state = abs_state
        model_state = jax.device_put(model_state, self._repl_sharding)
        if self.state is None:
            self.state = TrainState(placed, model_state,
                                    self.optimizer.init(placed),
                                    rng=loop_rng)
        else:
            self.state.params = placed
            self.state.model_state = model_state
            self.state.opt_state = self.optimizer.init(placed)

    # ------------------------------------------------------------------
    def _mesh_scoped(self, fn):
        """Wrap a (possibly jitted) step so every call — including the
        trace-triggering first one — runs under this trainer's mesh as
        the ACTIVE mesh, letting mesh-aware layers (ring attention)
        discover the compile(mesh=...) mesh instead of only the
        process default."""
        def wrapped(*a, **k):
            with mesh_lib.active_mesh(self.mesh):
                return fn(*a, **k)
        return wrapped

    def _state_plan(self):
        """The declarative sharded train-state layout: explicit jit
        shardings over (params, model_state, opt_state, rng) — params per
        the strategy rule tables, optimizer state WITH its params
        (ZeRO-style, ``sharding.opt_state_sharding_tree``), model state
        and rng replicated.  Batch entries stay ``None`` (inferred from
        the placed arguments) so the replicated-batch fallback path keeps
        compiling.  Returns ``(in_shardings, out_shardings)`` for
        ``build_train_step``."""
        st = self.state
        self._opt_shardings = sharding_lib.opt_state_sharding_tree(
            st.opt_state, st.params, self._param_shardings, self.mesh)
        # model_state as a PREFIX (one sharding covers the whole
        # subtree): training-mode state may grow keys (aux_loss) the
        # init-time structure doesn't have
        in_sh = (self._param_shardings, self._repl_sharding,
                 self._opt_shardings, self._repl_sharding, None, None)
        out_sh = (self._param_shardings, self._repl_sharding,
                  self._opt_shardings, None)
        return in_sh, out_sh

    def _build_train_step(self):
        self.ensure_initialized()
        in_sh, out_sh = self._state_plan()
        return build_train_step(self.model, self.loss_fn, self.optimizer,
                                compute_dtype=self.compute_dtype,
                                accum_steps=self.accum_steps,
                                in_shardings=in_sh, out_shardings=out_sh)

    def _build_eval_step(self, metrics: Optional[Sequence] = None):
        model = self.model
        metrics = self.metrics if metrics is None else list(metrics)
        loss_fn = self.loss_fn

        def eval_step(params, model_state, accs, loss_acc, x, y, mask):
            y_pred, eval_state = model.apply(params, model_state, x,
                                             training=False)
            new_accs = [m.update(a, y, y_pred, mask)
                        for m, a in zip(metrics, accs)]
            if loss_fn is not None:
                # include auxiliary penalties (regularizers / MoE aux)
                # per sample so the reported evaluate loss is comparable
                # with the training loss (Keras includes them too)
                from ..pipeline.api.keras.objectives import _batch_mean
                # sequence losses arrive per-position (batch, T, ...):
                # collapse to per-SAMPLE so masking stays (batch,)
                per_sample = _batch_mean(
                    loss_fn(y, y_pred) + _collect_aux(eval_state))
                w = mask.reshape(-1).astype(jnp.float32)
                # neutralize masked-out padding BEFORE weighting: padded
                # tail samples can legitimately be NaN (e.g. class_nll's
                # out-of-range guard on zero-padded labels rebased by
                # zero_based_label=False), and NaN * 0 is NaN
                per_sample = jnp.where(w > 0, per_sample, 0.0)
                loss_acc = {"sum": loss_acc["sum"]
                            + jnp.sum(per_sample * w),
                            "n": loss_acc["n"] + jnp.sum(w)}
            return new_accs, loss_acc

        return jax.jit(eval_step)

    def _build_predict_step(self):
        model = self.model

        def predict_step(params, model_state, x):
            y_pred, _ = model.apply(params, model_state, x, training=False)
            return y_pred

        # the batch buffer is freshly device_put per step by the prefetch
        # thread and never read after the step — donating it lets XLA
        # write activations into it instead of allocating.  CPU doesn't
        # implement input donation (it would warn per call), so gate it.
        donate = (2,) if jax.default_backend() in ("tpu", "gpu") else ()
        return jax.jit(predict_step, donate_argnums=donate)

    # ------------------------------------------------------------------
    _warned_replicated = False

    def _split_microbatches(self, x, y):
        """Host-side (accum, micro, ...) view of a batch — a zero-copy
        numpy reshape on the prefetch thread, attributed to the
        ``grad_accum`` profiler phase by the caller.  The scanned
        accumulation axis leads; the data axes shard dim 1."""
        accum = self.accum_steps

        def split(a):
            a = np.asarray(a)
            if a.shape[0] % accum:
                raise ValueError(
                    f"per-host batch ({a.shape[0]}) must divide "
                    f"accum_steps ({accum})")
            return a.reshape((accum, a.shape[0] // accum) + a.shape[1:])

        sx = (tuple(split(a) for a in x) if isinstance(x, (tuple, list))
              else split(x))
        if y is None:
            return sx, None
        sy = (tuple(split(a) for a in y) if isinstance(y, (tuple, list))
              else split(y))
        return sx, sy

    def _put_batch(self, x, y, microbatched: bool = False):
        """Place a host-local batch onto the mesh, per-shard: the
        ``device_put``/``make_array_from_process_local_data`` under
        ``put_global`` transfers each device's slice independently (and
        asynchronously), so upload overlaps compute across the mesh.
        Multi-host: ``x``/``y`` are this host's shard of the global batch
        and every process's shards are assembled into one global array
        (per-host feeding, reference net.py:458-468).  ``microbatched``
        batches arrive pre-split as (accum, micro, ...) — the data axes
        shard dim 1 and cross-process assembly concatenates there."""
        first = x[0] if isinstance(x, (tuple, list)) else x
        batch_dim = 1 if microbatched else 0
        dp = mesh_lib.dp_size(self.mesh)
        nproc = dist_lib.process_count()
        global_rows = np.shape(first)[batch_dim] * nproc
        divisible = global_rows % max(dp, 1) == 0
        if not divisible and nproc > 1:
            raise ValueError(
                f"global batch ({global_rows}) must divide the data-"
                f"parallel degree ({dp}) in multi-host execution")
        if not divisible and not Trainer._warned_replicated:
            # correct but every device redundantly computes the full batch
            Trainer._warned_replicated = True
            from ..observability.log import get_logger
            get_logger("analytics_zoo_tpu.train").warning(
                "batch does not divide the data-parallel degree — "
                "falling back to replicated compute (every device runs "
                "the full batch). Pad the batch for full speed.",
                batch=np.shape(first)[batch_dim], data_parallel=dp)
        if divisible:
            sharding = (self._microbatch_sharding if microbatched
                        else self._batch_sharding)
        else:
            sharding = self._repl_sharding
        put = lambda a: dist_lib.put_global(a, sharding,
                                            batch_sharded=divisible,
                                            batch_dim=batch_dim)
        xs = (tuple(put(a) for a in x) if isinstance(x, (tuple, list))
              else put(x))
        if y is None:
            return xs, None
        ys = (tuple(put(a) for a in y) if isinstance(y, (tuple, list))
              else put(y))
        return xs, ys

    def set_tensorboard(self, log_dir: str, app_name: str,
                        profile: bool = False, profile_steps: int = 10):
        """Parity: KerasNet.setTensorBoard (Topology.scala:157-175).

        ``profile=True`` additionally captures ONE ``jax.profiler`` trace
        per fit (the first ``profile_steps`` steps) under
        ``<log_dir>/<app_name>/plugins/profile`` so TensorBoard shows the
        step timeline alongside the scalars — the reference's ``timing()``
        wall-clock wrappers, upgraded to a real device trace
        (InferenceSupportive.scala:37-44; SURVEY §5)."""
        self.train_summary = TrainSummary(log_dir, app_name)
        self.val_summary = ValidationSummary(log_dir, app_name)
        self._profile_dir = (os.path.join(log_dir, app_name)
                             if profile else None)
        self._profile_steps = int(profile_steps)

    _profile_dir: Optional[str] = None
    _profile_steps: int = 10

    def set_checkpoint(self, path: str, over_write: bool = True,
                       trigger=None):
        """Parity: KerasNet.setCheckpoint (Topology.scala:184-194)."""
        self._ckpt_path = path
        self._ckpt_overwrite = over_write
        self._ckpt_trigger = trigger or trigger_lib.EveryEpoch()

    _ckpt_path: Optional[str] = None
    _ckpt_trigger = None
    _auto_resumed = False
    _resume_epoch_step = 0
    _step_profiler: "Optional[stepprof.StepProfiler]" = None

    def enable_step_profiler(self, timeline_path: Optional[str] = None
                             ) -> "stepprof.StepProfiler":
        """Turn on the per-step phase profiler (data_wait -> h2d ->
        step_compute -> ckpt_save; train/stepprof.py) for subsequent
        ``fit`` calls.  ``timeline_path`` additionally publishes the
        bounded per-step timeline as JSONL at fit end.  Also reachable
        without code changes via ``ZOO_STEP_PROFILE=1`` /
        ``ZOO_STEP_TIMELINE=<path>``."""
        self._step_profiler = stepprof.StepProfiler(
            timeline_path=timeline_path)
        return self._step_profiler

    def _maybe_auto_resume(self):
        """Supervised-restart contract: under ``ZOO_RESUME`` (set by the
        launcher on every pod relaunch) a checkpointing fit restores the
        newest COMPLETE snapshot before training.  No complete snapshot
        → clean cold start (coarse-grained recovery may cost lost steps,
        never a torn restore)."""
        if (self._ckpt_path is None or not faults.resume_requested()
                or self._auto_resumed
                or self.state.step or self.state.epoch):
            return
        self._auto_resumed = True
        from ..observability.log import get_logger
        slog = get_logger("analytics_zoo_tpu.train")
        try:
            self.load_weights(self._ckpt_path)
        except FileNotFoundError:
            train_metrics.record_ckpt_restore("cold_start")
            slog.warning(
                "ZOO_RESUME set but no complete checkpoint found — "
                "cold start", path=self._ckpt_path)
            return
        except Exception as e:
            # a torn/unreadable checkpoint (e.g. a crash during the
            # FIRST save, before any commit existed, leaves a legacy-
            # looking directory) must never be worse than a cold start
            # under the supervisor contract — a raise here would
            # crash-loop every resumed incarnation.  The explicit
            # load_weights path still fails loudly.
            train_metrics.record_ckpt_restore("cold_start")
            slog.error(
                "ZOO_RESUME restore failed — cold start",
                path=self._ckpt_path,
                error=f"{type(e).__name__}: {e}")
            return
        slog.info("resumed from checkpoint", path=self._ckpt_path,
                  epoch=self.state.epoch, step=self.state.step,
                  epoch_step=self._resume_epoch_step)

    # ------------------------------------------------------------------
    def fit(self, dataset: Dataset, batch_size: int, end_trigger=None,
            validation_data: Optional[Dataset] = None,
            validation_trigger=None, validation_batch_size: int = None,
            shuffle: bool = True, verbose: bool = False) -> Dict[str, List]:
        """Run the optimization loop until ``end_trigger`` fires.

        Returns a history dict of per-iteration losses and validation
        results.  Successive calls continue from the current epoch
        (incremental-fit parity).

        ``batch_size`` is the GLOBAL batch.  In multi-host execution each
        process feeds ``batch_size // process_count`` rows of its local
        dataset shard per step (per-host feeding, reference
        net.py:458-468); single-process it is the whole batch."""
        self.ensure_initialized()
        faults.refresh()  # supervisor env contract (heartbeat/faults)
        faults.heartbeat()
        # cross-process observability: the flight recorder (black box
        # the supervisor harvests on abnormal exit) and the step
        # profiler both arm from the env contract; each costs one None
        # check per step when absent
        recorder = flightrec.install_from_env()
        prof = self._step_profiler
        if prof is None:
            prof = self._step_profiler = stepprof.from_env()
        if recorder is not None:
            # add_collector dedups by function identity, so wiring on
            # every fit is free AND survives a recorder being replaced
            # (shutdown + re-configure) between fits
            recorder.add_collector(train_metrics.train_families)
            if prof is not None:
                recorder.add_collector(prof.families)
        self._maybe_auto_resume()
        # mid-epoch resume (iteration-trigger checkpoints): skip the
        # batches the restored position already consumed so the replayed
        # step sequence matches the uninterrupted run deterministically
        resume_skip = int(self._resume_epoch_step or 0)
        self._resume_epoch_step = 0
        if self._train_step is None:
            self._train_step = self._mesh_scoped(
                self._build_train_step())
        check_batch_divisibility(batch_size, mesh_lib.dp_size(self.mesh),
                                 dist_lib.process_count())
        per_host_bs = batch_size // dist_lib.process_count()
        if per_host_bs % self.accum_steps:
            raise ValueError(
                f"per-host batch ({per_host_bs}) must divide "
                f"accum_steps ({self.accum_steps}) — every microbatch "
                "keeps one compiled shape")
        end_trigger = end_trigger or trigger_lib.MaxEpoch(
            self.state.epoch + 1)
        validation_trigger = validation_trigger or trigger_lib.EveryEpoch()
        history: Dict[str, List] = {"loss": [], "val": []}
        st = self.state

        lr_fn = getattr(self.optimizer, "lr_fn", None)
        stop = False
        # one profiler trace per fit (default off): first N steps
        profiling = False
        profile_end_step = None
        if self._profile_dir is not None:
            try:
                jax.profiler.start_trace(self._profile_dir)
                profiling = True
                profile_end_step = st.step + self._profile_steps
            except Exception as e:  # tracing is best-effort telemetry
                import logging
                logging.getLogger("analytics_zoo_tpu").warning(
                    "could not start jax.profiler trace: %s", e)

        def _stop_profile():
            nonlocal profiling
            if profiling:
                profiling = False
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass

        try:
            while True:
                record = {"epoch": st.epoch, "iteration": st.step}
                if stop or end_trigger(record):
                    break
                epoch_start, epoch_samples = time.time(), 0
                # per-epoch device-side loss buffer: NO per-step host sync —
                # losses stay on device and are fetched in one bulk transfer at
                # the epoch boundary (the round-1 `float(loss)` per step
                # destroyed async dispatch).  Loss-dependent triggers (MinLoss)
                # still work: the record carries the device scalar and only
                # such a trigger pays the sync.
                epoch_losses = []
                epoch_start_step = st.step - resume_skip
                batch_it = dataset.batches(per_host_bs, shuffle=shuffle,
                                           seed=self.seed, epoch=st.epoch)
                if resume_skip:
                    # the epoch's batch order is deterministic in
                    # (seed, epoch); dropping the first k batches is the
                    # data-pipeline fast-forward to the restored step
                    batch_it = itertools.islice(batch_it, resume_skip,
                                                None)
                    resume_skip = 0
                accum = self.accum_steps
                if prof is None:
                    if accum == 1:
                        put_fn = lambda b: self._put_batch(*b)
                    else:
                        put_fn = lambda b: self._put_batch(
                            *self._split_microbatches(*b),
                            microbatched=True)
                else:
                    def put_fn(b):
                        # grad_accum (host microbatch split) and h2d
                        # measured ON the prefetch thread, shipped with
                        # the batch so the consuming step's span can
                        # attribute them
                        accum_s = 0.0
                        if accum > 1:
                            t0 = time.perf_counter()
                            b = self._split_microbatches(*b)
                            accum_s = time.perf_counter() - t0
                        t0 = time.perf_counter()
                        out = self._put_batch(*b, microbatched=accum > 1)
                        return out, time.perf_counter() - t0, accum_s
                dev_it = prefetch_iterator(batch_it, put_fn)
                step_it = (dev_it if prof is None
                           else prof.timed_iter(dev_it))
                for item in step_it:
                    if prof is None:
                        bx, by = item
                        span = None
                    else:
                        (bx, by), h2d_s, accum_s = item
                        span = prof.begin_step(st.step + 1, h2d_s,
                                               accum_s=accum_s)
                    step_rng = jax.random.fold_in(st.rng, st.step)
                    if span is None:
                        st.params, st.model_state, st.opt_state, loss = \
                            self._train_step(st.params, st.model_state,
                                             st.opt_state, step_rng,
                                             bx, by)
                    else:
                        # the span is ACTIVE across the dispatch so
                        # backend_compile events attribute to the exact
                        # step that paid the compile
                        span.phase_start("step_compute")
                        with trace_lib.activate(span):
                            st.params, st.model_state, st.opt_state, \
                                loss = self._train_step(
                                    st.params, st.model_state,
                                    st.opt_state, step_rng, bx, by)
                        span.phase_end()
                    st.step += 1
                    faults.heartbeat()
                    train_metrics.record_step()
                    if recorder is not None:
                        # liveness marker BEFORE the fault hook: a
                        # crash at step k must leave the step-k record
                        # (the postmortem's "last completed step")
                        recorder.record_step(st.step)
                        if not st.step & 15:
                            # throttle-CHECK every 16th step: the call
                            # itself is measurable in a contended loop
                            # and the snapshot cadence is seconds
                            recorder.snapshot_metrics()
                    # injected faults land BEFORE the checkpoint trigger:
                    # a crash at step k must never leave a step-k tag
                    faults.maybe_fault(st.step)
                    epoch_samples += batch_size
                    epoch_losses.append(loss)
                    if profiling and st.step >= profile_end_step:
                        jax.block_until_ready(loss)  # trace covers real work
                        _stop_profile()
                    it_record = {"epoch": st.epoch, "iteration": st.step,
                                 "loss": loss}
                    if self._ckpt_path and not isinstance(
                            self._ckpt_trigger, trigger_lib.EveryEpoch) \
                            and self._ckpt_trigger(it_record):
                        if span is not None:
                            span.phase_start("ckpt_save")
                        save = (save_sharded if faults.sync_checkpoints()
                                else async_save_sharded)
                        save(self._ckpt_path, st.step, st.as_tree(),
                             meta={"step": st.step, "epoch": st.epoch,
                                   "epoch_step":
                                       st.step - epoch_start_step})
                        if span is not None:
                            span.phase_end()
                    if span is not None:
                        prof.finish_step(span, st.step)
                    if end_trigger(it_record):
                        # remember the firing so the outer loop terminates even
                        # for triggers the outer record can't re-evaluate
                        # (e.g. MinLoss — the per-epoch record carries no loss)
                        stop = True
                        break
                # stop the worker deterministically — an iteration-level
                # end trigger breaks out with batches still buffered
                dev_it.close()
                st.epoch += 1
                # one bulk host transfer for the whole epoch's scalars
                losses_host = ([float(v) for v in
                                np.asarray(jax.device_get(epoch_losses))]
                               if epoch_losses else [])
                base_step = st.step - len(losses_host)
                history["loss"].extend(losses_host)
                elapsed = max(time.time() - epoch_start, 1e-9)
                if self.train_summary is not None:
                    # add_scalar self-gates on any set_summary_trigger
                    for i, lossf in enumerate(losses_host):
                        step_i = base_step + i + 1
                        self.train_summary.add_scalar("Loss", lossf, step_i)
                        if lr_fn is not None:
                            self.train_summary.add_scalar(
                                "LearningRate", float(lr_fn(step_i - 1)),
                                step_i)
                    self.train_summary.add_scalar(
                        "Throughput", epoch_samples / elapsed, st.step)
                    self.train_summary.flush()
                epoch_record = {"epoch": st.epoch, "iteration": st.step,
                                "epoch_finished": True,
                                "loss": history["loss"][-1]
                                if history["loss"] else None}
                if verbose:
                    # a resumed epoch whose checkpoint sat exactly on
                    # the epoch boundary replays zero batches: no loss
                    lossf = epoch_record["loss"]
                    print(f"[zoo-tpu] epoch {st.epoch} step {st.step} "
                          f"loss "
                          f"{'n/a' if lossf is None else f'{lossf:.4f}'} "
                          f"({epoch_samples / elapsed:.0f} samples/s)")
                if validation_data is not None and validation_trigger(
                        epoch_record):
                    results = self.evaluate(validation_data,
                                            validation_batch_size or batch_size)
                    history["val"].append({"epoch": st.epoch, **results})
                    if self.val_summary is not None:
                        for k, v in results.items():
                            self.val_summary.add_scalar(k, v, st.step)
                        self.val_summary.flush()
                    if verbose:
                        print(f"[zoo-tpu]   validation: {results}")
                faults.heartbeat()
                if self._ckpt_path and isinstance(self._ckpt_trigger,
                                                  trigger_lib.EveryEpoch):
                    async_save_sharded(self._ckpt_path, f"epoch{st.epoch}",
                                       st.as_tree(),
                                       meta={"step": st.step,
                                             "epoch": st.epoch,
                                             "epoch_step": 0})
        finally:
            # the trace must stop even when fit raises mid-epoch, or
            # profiling stays broken for the process ('trace already
            # started')
            _stop_profile()
            if prof is not None:
                prof.flush(recorder)  # buffered step entries
                try:
                    prof.write_timeline()
                except OSError as e:
                    from ..observability.log import get_logger
                    get_logger("analytics_zoo_tpu.train").warning(
                        "could not write step timeline",
                        path=prof.timeline_path,
                        error=f"{type(e).__name__}: {e}")
            if recorder is not None:
                recorder.snapshot_metrics(force=True)
        if self._ckpt_path:
            # fit returning means "checkpoints are on disk" — join the
            # async writers, then barrier so EVERY pod process's shards
            # are on disk before any process restores
            checkpoint_lib_wait_pending(self._ckpt_path)
            from .checkpoint import _pod_barrier
            _pod_barrier("zoo_fit_ckpt_done")
        return history

    # ------------------------------------------------------------------
    def evaluate(self, dataset: Dataset, batch_size: int,
                 metrics: Optional[Sequence] = None) -> Dict[str, float]:
        """Evaluate over the FULL dataset — the trailing partial batch is
        padded to the compiled batch shape and masked out of every metric,
        so n % batch_size != 0 loses no samples (reference evaluates the
        whole set, Topology.scala:353).

        ``metrics`` overrides the compiled metric set for this call —
        parity with the reference's ``evaluate(rdd, batch, valMethods)``
        (Topology.scala:353); names or Metric instances.
        """
        self.ensure_initialized()
        if metrics is None:
            use_metrics = self.metrics
            if self._eval_step is None:
                self._eval_step = self._mesh_scoped(
                    self._build_eval_step())
            eval_step = self._eval_step
        else:
            from ..pipeline.api.keras import metrics as metrics_lib
            zero_based = getattr(self.loss_fn, "zero_based_label", True)
            use_metrics = [metrics_lib.get(m, zero_based_label=zero_based)
                           for m in metrics]
            # cache override steps by the metrics' FULL config so an
            # epoch loop with the same valMethods doesn't re-jit, while a
            # custom Metric subclass differing in any constructor
            # attribute (not just name/k/neg_num) gets its own step
            def _metric_key(m):
                # callables are keyed by OBJECT (identity compare, and
                # the key tuple keeps them alive so ids can't be
                # recycled); everything else by repr
                cfg = tuple(sorted(
                    (k, v if callable(v) else repr(v))
                    for k, v in vars(m).items()))
                return (type(m).__module__, type(m).__qualname__,
                        m.name, cfg)
            key = tuple(_metric_key(m) for m in use_metrics)
            if self._eval_step_overrides.get("key") != key:
                self._eval_step_overrides = {
                    "key": key,
                    "step": self._mesh_scoped(
                        self._build_eval_step(use_metrics))}
            eval_step = self._eval_step_overrides["step"]
        accs = [m.init() for m in use_metrics]
        loss_acc = {"sum": jnp.zeros(()), "n": jnp.zeros(())}
        dp = mesh_lib.dp_size(self.mesh)
        nproc = dist_lib.process_count()
        per_host_bs = max(batch_size // nproc, 1)
        if nproc > 1:
            # the pod must run sharded — round the per-host batch up so
            # the global batch divides dp (padding is masked out anyway)
            if dp % nproc != 0:
                raise ValueError(
                    f"data-parallel degree ({dp}) must be a multiple of "
                    f"the process count ({nproc}) for multi-host evaluate")
            local_dp = dp // nproc
            per_host_bs = -(-per_host_bs // local_dp) * local_dp
        batch_size = per_host_bs * nproc
        sharded = batch_size % max(dp, 1) == 0
        mask_sharding = (self._batch_sharding if sharded
                         else self._repl_sharding)
        full_mask = dist_lib.put_global(
            np.ones((per_host_bs if sharded else batch_size,), np.float32),
            mask_sharding, batch_sharded=sharded)
        # per-row validity from shard_by_process wrap-around fillers:
        # they keep the pod in lockstep but must not count in metrics
        valid = getattr(dataset, "valid", None)
        offset = 0
        for bx, by in dataset.batches(per_host_bs, shuffle=False,
                                      drop_remainder=False):
            first = bx[0] if isinstance(bx, (tuple, list)) else bx
            n_real = len(first)
            v_slice = (None if valid is None
                       else valid[offset:offset + n_real])
            offset += n_real
            if v_slice is not None and v_slice.all():
                v_slice = None  # fully valid: reuse the cached full mask
            if n_real < per_host_bs or v_slice is not None:
                pad = per_host_bs - n_real
                if pad:
                    bx = _pad_tail(bx, pad)
                    if by is not None:
                        by = _pad_tail(by, pad)
                mask = np.zeros((per_host_bs,), np.float32)
                mask[:n_real] = (1.0 if v_slice is None
                                 else v_slice.astype(np.float32))
                # multi-host always runs sharded (rounded above), so the
                # replicated branch only exists single-process
                mask_dev = dist_lib.put_global(mask, mask_sharding,
                                               batch_sharded=sharded)
            else:
                mask_dev = full_mask
            faults.heartbeat()
            bx, by = self._put_batch(bx, by)
            accs, loss_acc = eval_step(
                self.state.params, self.state.model_state, accs, loss_acc,
                bx, by, mask_dev)
        results = {m.name: float(m.result(a))
                   for m, a in zip(use_metrics, accs)}
        if self.loss_fn is not None and float(loss_acc["n"]) > 0:
            results["loss"] = float(loss_acc["sum"]) / float(loss_acc["n"])
        return results

    # ------------------------------------------------------------------
    def predict(self, dataset_or_x, batch_size: int = 32) -> Any:
        """Forward the dataset.  ``batch_size`` is global; multi-host, each
        process feeds its local shard and receives its own rows back (the
        reference's partition-local predict, Topology.scala:393-397)."""
        self.ensure_initialized()
        if self._predict_step is None:
            self._predict_step = self._mesh_scoped(
                self._build_predict_step())
        if isinstance(dataset_or_x, Dataset):
            ds = dataset_or_x
        else:
            ds = Dataset.from_ndarray(dataset_or_x)
        outs = []
        n = ds.size
        if n == 0:  # size None (unknown stream length) passes through
            raise ValueError("predict called with an empty dataset")
        nproc = dist_lib.process_count()
        per_host_bs = max(batch_size // nproc, 1)
        if nproc > 1:
            # same rounding as evaluate: the pod must run sharded
            dp = mesh_lib.dp_size(self.mesh)
            if dp % nproc != 0:
                raise ValueError(
                    f"data-parallel degree ({dp}) must be a multiple of "
                    f"the process count ({nproc}) for multi-host predict")
            local_dp = dp // nproc
            per_host_bs = -(-per_host_bs // local_dp) * local_dp
        def _prep(batch):
            """Host-side pad + device_put — runs on the prefetch thread,
            overlapped with the previous batch's device compute."""
            bx, _ = batch
            pad = 0
            first = bx[0] if isinstance(bx, (tuple, list)) else bx
            if len(first) < per_host_bs:
                # pad the trailing batch to keep one compiled shape
                pad = per_host_bs - len(first)
                bx = _pad_tail(bx, pad)
            placed, _ = self._put_batch(bx, None)
            return placed, pad

        from ..common.prefetch import prefetch
        dev_it = prefetch(ds.batches(per_host_bs, shuffle=False,
                                     drop_remainder=False), _prep)
        for bx, pad in dev_it:
            y = self._predict_step(self.state.params, self.state.model_state,
                                   bx)
            # multi-host: fetch only the rows this host fed
            y = jax.tree_util.tree_map(dist_lib.local_rows, y)
            if pad:
                y = jax.tree_util.tree_map(lambda a: a[:-pad], y)
            outs.append(y)
        if isinstance(outs[0], (tuple, list)):
            return type(outs[0])(
                np.concatenate([o[i] for o in outs])[:n]
                for i in range(len(outs[0])))
        return np.concatenate(outs)[:n]

    # ------------------------------------------------------------------
    def save_weights(self, directory: str, tag="final"):
        """Per-shard save: each pod process writes only its addressable
        shards (no host-0 gather) — SURVEY §5's sharded-TrainState story."""
        from .checkpoint import save_sharded
        self.ensure_initialized()
        save_sharded(directory, tag, self.state.as_tree(),
                     meta={"step": self.state.step,
                           "epoch": self.state.epoch})

    def load_weights(self, directory: str, tag=None):
        """Restore with RE-SHARDING: the checkpoint's global leaves are
        re-placed under this trainer's shardings, so a snapshot taken on a
        different mesh shape or strategy restores cleanly."""
        from .checkpoint import restore_sharded, read_meta
        from jax.sharding import NamedSharding
        self.ensure_initialized()
        template = self.state.as_tree()

        def target_sharding(l):
            if not isinstance(l, jax.Array):
                return None
            # leaves born off-mesh (e.g. optax's scalar step count gets a
            # SingleDeviceSharding at init) must land replicated on the
            # mesh, or the restored state pins jit to one device
            if isinstance(l.sharding, NamedSharding):
                return l.sharding
            return self._repl_sharding

        shardings = jax.tree_util.tree_map(target_sharding, template)
        tree = restore_sharded(directory, template, tag,
                               shardings=shardings)
        self.state.load_tree(tree)
        meta = read_meta(directory, tag)
        self.state.step = int(meta.get("step", self.state.step))
        self.state.epoch = int(meta.get("epoch", self.state.epoch))
        # iteration-trigger snapshots land mid-epoch: the next fit()
        # fast-forwards this many batches into the restored epoch
        self._resume_epoch_step = int(meta.get("epoch_step", 0))
