"""The ``ZOO_*`` environment-variable contract, in one place.

Every environment variable the package reads under the ``ZOO_`` prefix
is declared in :data:`VARS` and read through the accessors below —
nothing else in the tree touches ``os.environ`` for a ``ZOO_*`` name.
zoolint's ZL812 enforces the discipline statically (any scattered
``os.environ`` read of a ``ZOO_*`` name outside this module is a
finding), and ``zoolint contracts`` renders :data:`VARS` into the
committed ``contracts_snapshot.json`` so adding a knob is an explicit
reviewed hunk, with the docs tables in ``docs/serving.md`` /
``docs/distributed-training.md`` kept in lockstep.

Why centralize: before this module the reads were scattered across
``train/faults.py``, ``observability/flightrec.py``, ``serving/fleet``
and ``serving/execstore.py`` — renaming a variable (or auditing what a
deployment may set) meant grepping, and two modules could silently
disagree on parsing (int vs flag).  The accessors fix the parse
semantics per call site and the table fixes the vocabulary.

The legacy ``ENV_*`` module constants (``faults.ENV_RESUME``,
``flightrec.ENV_DIR``, ...) remain as aliases for external callers;
their values are the canonical names declared here.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# name -> one-line purpose.  The single source of truth for the env
# contract: the docs tables, the ZL812 rule, and the contracts
# snapshot all derive from this dict.
VARS: Dict[str, str] = {
    "ZOO_TPU_COORDINATOR": "coordinator address for multi-process jax.distributed init",
    "ZOO_TPU_NUM_PROCESSES": "process count for multi-process jax.distributed init",
    "ZOO_TPU_PROCESS_ID": "this process's rank in the pod (also stamps logs/metrics)",
    "ZOO_RESTART_COUNT": "supervisor-maintained incarnation counter for elastic restarts",
    "ZOO_RESUME": "flag: this incarnation is a restart and must resume from checkpoint",
    "ZOO_HEARTBEAT_FILE": "path the worker touches per step/loop for liveness detection",
    "ZOO_CKPT_SYNC": "flag: force synchronous (blocking) checkpoint saves",
    "ZOO_FAULT_CRASH_STEP": "fault drill: step at which the chosen rank hard-crashes",
    "ZOO_FAULT_CRASH_RANK": "fault drill: rank that crashes at ZOO_FAULT_CRASH_STEP",
    "ZOO_FAULT_HANG_STEP": "fault drill: step at which the chosen rank hangs",
    "ZOO_FAULT_HANG_RANK": "fault drill: rank that hangs at ZOO_FAULT_HANG_STEP",
    "ZOO_FAULT_CORRUPT_TAG": "fault drill: checkpoint tag to corrupt on save",
    "ZOO_FLIGHTREC_DIR": "directory for flight-recorder ring dumps and post-mortems",
    "ZOO_STEP_PROFILE": "flag: enable the per-step training profiler",
    "ZOO_STEP_TIMELINE": "path for the step profiler's JSON timeline dump",
    "ZOO_EXECSTORE_DIR": "root directory of the persistent executable store",
    "ZOO_EXECSTORE_BYTES": "byte budget for the executable store's LRU eviction",
    "ZOO_PAGER_RESIDENT": "worker pager residency budget (max resident models)",
    "ZOO_FLEET_WIRE": "fleet wire encoding override: 'json' disables binary frames",
    "ZOO_FLEET_MAX_FRAME": "max accepted fleet frame size in bytes (DoS guard)",
    "ZOO_TRACE_TAIL_Q": "tail-sampling retention quantile in (0,1) for exemplar traces (default 0.95; out-of-range disables)",
    "ZOO_TRACE_TAIL_CAP": "max tail-retained exemplar span trees per process (default 64)",
    "ZOO_TRAIN_STRATEGY": "default Trainer sharding strategy (replicate|fsdp|tp|fsdp_tp); constructor arg wins",
    "ZOO_TRAIN_ACCUM": "gradient-accumulation microbatches per optimizer step (default 1 = off)",
    "ZOO_TRAIN_DTYPE": "training compute dtype: 'bf16' enables mixed precision (f32 master weights); default full f32",
}


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw string value of a declared ``ZOO_*`` variable.

    Empty values fall through to ``default`` — an exported-but-empty
    variable means "unset" everywhere in this package.
    """
    if name not in VARS:
        raise KeyError(f"undeclared env var {name!r}: add it to "
                       "envcontract.VARS (and the docs table)")
    return os.environ.get(name) or default


def env_int(name: str, default: int = 0) -> int:
    """Integer parse of a declared variable; unset/empty/garbage all
    yield ``default`` (an operator typo must degrade, not crash a
    worker at import)."""
    raw = env_str(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_flag(name: str) -> bool:
    """Truthiness of a declared variable: any non-empty value is on
    (the historical ``bool(os.environ.get(...))`` semantics every
    caller already relied on)."""
    return env_str(name) is not None
