"""3D (medical) image transforms.

Parity surface: reference zoo/.../feature/image3d/{Rotation.scala:32-61,
Affine.scala, Cropper.scala:34, ImageFeature3D.scala} — Rotate3D (Euler
rotation matrix), AffineTransform3D (matrix + translation with trilinear
resampling), Crop3D/RandomCrop3D/CenterCrop3D.

Volumes are DHW(×C) float32 numpy arrays; resampling uses
scipy.ndimage.affine_transform (host-side, like every input-pipeline stage).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from scipy import ndimage

from ..common import Preprocessing, register_preprocessing
from ..image.transforms import ImageFeature


class ImageFeature3D(ImageFeature):
    """Per-volume record (reference ImageFeature3D.scala)."""


def _as_feature3d(sample) -> ImageFeature3D:
    if isinstance(sample, ImageFeature3D):
        return sample
    if isinstance(sample, ImageFeature):
        f = ImageFeature3D(sample)
        return f
    f = ImageFeature3D()
    if isinstance(sample, dict):
        # a plain {'image': volume, ...} record is a feature, not pixels
        if "image" not in sample:
            raise ValueError(
                "dict sample for a 3D transform needs an 'image' key")
        f.update(sample)
    else:
        f["image"] = sample
    return f


class ImageProcessing3D(Preprocessing):
    def apply(self, sample):
        f = _as_feature3d(sample)
        f["image"] = self.transform(np.asarray(f["image"],
                                               dtype=np.float32))
        return f

    def transform(self, vol: np.ndarray) -> np.ndarray:
        raise NotImplementedError


def rotation_matrix(yaw: float, pitch: float, roll: float) -> np.ndarray:
    """Euler-angle rotation matrix (reference Rotation.scala:36-61)."""
    cy, sy = np.cos(yaw), np.sin(yaw)
    cp, sp = np.cos(pitch), np.sin(pitch)
    cr, sr = np.cos(roll), np.sin(roll)
    rz = np.array([[cy, -sy, 0], [sy, cy, 0], [0, 0, 1]])
    ry = np.array([[cp, 0, sp], [0, 1, 0], [-sp, 0, cp]])
    rx = np.array([[1, 0, 0], [0, cr, -sr], [0, sr, cr]])
    return rz @ ry @ rx


@register_preprocessing
class AffineTransform3D(ImageProcessing3D):
    """Affine warp: v' = A(v - c) + c + t, trilinear interpolation
    (reference Affine.scala)."""

    def __init__(self, mat: Sequence[Sequence[float]] = None,
                 translation: Sequence[float] = (0, 0, 0),
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        self.mat = np.asarray(mat, dtype=np.float64)
        self.translation = np.asarray(translation, dtype=np.float64)
        self.clamp_mode = clamp_mode
        self.pad_val = float(pad_val)

    def transform(self, vol):
        squeeze = False
        if vol.ndim == 4 and vol.shape[-1] == 1:
            vol, squeeze = vol[..., 0], True
        center = (np.asarray(vol.shape) - 1) / 2.0
        # inverse map: output voxel -> input voxel
        inv = np.linalg.inv(self.mat)
        offset = center - inv @ (center + self.translation)
        mode = "nearest" if self.clamp_mode == "clamp" else "constant"
        out = ndimage.affine_transform(
            vol, inv, offset=offset, order=1, mode=mode,
            cval=self.pad_val).astype(np.float32)
        return out[..., None] if squeeze else out

    def get_config(self):
        return {"mat": self.mat.tolist(),
                "translation": self.translation.tolist(),
                "clamp_mode": self.clamp_mode, "pad_val": self.pad_val}


@register_preprocessing
class Rotate3D(AffineTransform3D):
    """Rotation by Euler angles (reference Rotation.scala:32)."""

    def __init__(self, rotation_angles: Sequence[float] = (0, 0, 0)):
        self.rotation_angles = tuple(float(a) for a in rotation_angles)
        super().__init__(mat=rotation_matrix(*self.rotation_angles))

    def get_config(self):
        return {"rotation_angles": list(self.rotation_angles)}


@register_preprocessing
class Crop3D(ImageProcessing3D):
    """Crop a patch at ``start`` (DHW) of size ``patch_size``
    (reference Cropper.scala:34)."""

    def __init__(self, start: Sequence[int] = None,
                 patch_size: Sequence[int] = None):
        self.start = tuple(int(s) for s in start)
        self.patch_size = tuple(int(s) for s in patch_size)

    def transform(self, vol):
        z, y, x = self.start
        d, h, w = self.patch_size
        return vol[z:z + d, y:y + h, x:x + w]

    def get_config(self):
        return {"start": list(self.start),
                "patch_size": list(self.patch_size)}


@register_preprocessing
class CenterCrop3D(ImageProcessing3D):
    def __init__(self, patch_size: Sequence[int] = None):
        self.patch_size = tuple(int(s) for s in patch_size)

    def transform(self, vol):
        starts = [(dim - p) // 2
                  for dim, p in zip(vol.shape[:3], self.patch_size)]
        return Crop3D(starts, self.patch_size).transform(vol)

    def get_config(self):
        return {"patch_size": list(self.patch_size)}


@register_preprocessing
class RandomCrop3D(ImageProcessing3D):
    def __init__(self, patch_size: Sequence[int] = None, seed: int = 0):
        self.patch_size = tuple(int(s) for s in patch_size)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, vol):
        starts = [int(self.rng.integers(0, dim - p + 1))
                  for dim, p in zip(vol.shape[:3], self.patch_size)]
        return Crop3D(starts, self.patch_size).transform(vol)

    def get_config(self):
        return {"patch_size": list(self.patch_size), "seed": self.seed}


# reference-name alias (transformation.py ImagePreprocessing3D)
ImagePreprocessing3D = ImageProcessing3D
