from .transforms import (ImageFeature3D, Rotate3D, AffineTransform3D,
                         Crop3D, CenterCrop3D, RandomCrop3D,
                         rotation_matrix, ImageProcessing3D,
                         ImagePreprocessing3D)
