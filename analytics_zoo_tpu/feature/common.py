"""Preprocessing: composable feature-engineering transformers.

Parity surface: reference zoo/.../feature/common/*.scala —
``Preprocessing[A,B]`` with ``->`` chaining, and the adapter set
(SeqToTensor, ArrayToTensor, ScalarToTensor, MLlibVectorToTensor,
TensorToSample, FeatureLabelPreprocessing, FeatureToTupleAdapter,
BigDLAdapter); python mirror pyzoo/zoo/feature/common.py:25-130.

Chaining uses ``>>`` (Python's closest spelling of the reference's ``->``);
``ChainedPreprocessing([a, b, c])`` matches the pyzoo surface.  Transforms
run host-side on numpy (the input pipeline's domain); device work starts at
the batch boundary.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class Preprocessing:
    """A serializable transformer of single samples."""

    def apply(self, sample):
        raise NotImplementedError

    def __call__(self, sample):
        return self.apply(sample)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        """``a >> b``: feed a's output to b (reference ``->``)."""
        return ChainedPreprocessing([self, other])

    def map(self, iterable):
        return (self.apply(s) for s in iterable)

    # config round-trip for ML-pipeline persistence (NNEstimator.scala
    # serializes its Preprocessing with the model)
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config):
        return cls(**config)


_PREPROCESSING_REGISTRY = {}


def register_preprocessing(klass):
    _PREPROCESSING_REGISTRY[klass.__name__] = klass
    return klass


def preprocessing_to_spec(p: Preprocessing) -> dict:
    if isinstance(p, ChainedPreprocessing):
        return {"class_name": "ChainedPreprocessing",
                "stages": [preprocessing_to_spec(s) for s in p.stages]}
    return {"class_name": type(p).__name__, "config": p.get_config()}


def preprocessing_from_spec(spec: dict) -> Preprocessing:
    if spec["class_name"] == "ChainedPreprocessing":
        return ChainedPreprocessing(
            [preprocessing_from_spec(s) for s in spec["stages"]])
    klass = _PREPROCESSING_REGISTRY[spec["class_name"]]
    return klass.from_config(spec.get("config", {}))


@register_preprocessing
class ChainedPreprocessing(Preprocessing):
    def __init__(self, stages: Sequence[Preprocessing]):
        self.stages: List[Preprocessing] = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, sample):
        for s in self.stages:
            sample = s.apply(sample)
        return sample


@register_preprocessing
class SeqToTensor(Preprocessing):
    """Sequence of numbers -> ndarray with optional shape
    (reference SeqToTensor.scala)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = tuple(size) if size else None

    def apply(self, sample):
        arr = np.asarray(sample, dtype=np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr

    def get_config(self):
        return {"size": list(self.size) if self.size else None}


@register_preprocessing
class ArrayToTensor(SeqToTensor):
    """reference ArrayToTensor.scala (same semantics on numpy)."""


@register_preprocessing
class ScalarToTensor(Preprocessing):
    """reference ScalarToTensor.scala."""

    def apply(self, sample):
        return np.asarray([sample], dtype=np.float32)


@register_preprocessing
class MLlibVectorToTensor(Preprocessing):
    """Accepts anything with toArray()/values or array-like
    (reference MLlibVectorToTensor.scala)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = tuple(size) if size else None

    def apply(self, sample):
        if hasattr(sample, "toArray"):
            arr = np.asarray(sample.toArray(), dtype=np.float32)
        elif hasattr(sample, "values"):
            arr = np.asarray(sample.values, dtype=np.float32)
        else:
            arr = np.asarray(sample, dtype=np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr

    def get_config(self):
        return {"size": list(self.size) if self.size else None}


@register_preprocessing
class TensorToSample(Preprocessing):
    """Feature tensor -> (feature, None) sample (reference
    TensorToSample.scala; a Sample here is just an (x, y) tuple)."""

    def apply(self, sample):
        return (sample, None)


@register_preprocessing
class FeatureLabelPreprocessing(Preprocessing):
    """Zip a feature chain and a label chain over (feature, label) pairs
    (reference FeatureLabelPreprocessing.scala)."""

    def __init__(self, feature_preprocessing: Preprocessing,
                 label_preprocessing: Optional[Preprocessing] = None):
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing

    def apply(self, sample):
        feature, label = sample
        f = self.feature_preprocessing.apply(feature)
        l = (self.label_preprocessing.apply(label)
             if self.label_preprocessing is not None and label is not None
             else label)
        return (f, l)

    def get_config(self):
        return {
            "feature_preprocessing":
                preprocessing_to_spec(self.feature_preprocessing),
            "label_preprocessing":
                None if self.label_preprocessing is None
                else preprocessing_to_spec(self.label_preprocessing),
        }

    @classmethod
    def from_config(cls, config):
        return cls(
            preprocessing_from_spec(config["feature_preprocessing"]),
            None if config.get("label_preprocessing") is None
            else preprocessing_from_spec(config["label_preprocessing"]))


@register_preprocessing
class FeatureToTupleAdapter(Preprocessing):
    """Apply a feature preprocessing, pass label through
    (reference FeatureToTupleAdapter.scala)."""

    def __init__(self, preprocessing: Preprocessing):
        self.preprocessing = preprocessing

    def apply(self, sample):
        feature, label = sample
        return (self.preprocessing.apply(feature), label)

    def get_config(self):
        return {"preprocessing": preprocessing_to_spec(self.preprocessing)}

    @classmethod
    def from_config(cls, config):
        return cls(preprocessing_from_spec(config["preprocessing"]))


@register_preprocessing
class ToTuple(Preprocessing):
    """Wrap a bare feature into a (feature, None-label) tuple
    (reference common.py:125 ToTuple)."""

    def apply(self, sample):
        if isinstance(sample, tuple):
            return sample
        return (sample, None)


@register_preprocessing
class BigDLAdapter(Preprocessing):
    """Identity adapter kept for API parity (reference BigDLAdapter.scala
    wraps a BigDL Transformer; here any callable slots in directly)."""

    def __init__(self, fn: Optional[Callable] = None):
        self.fn = fn

    def apply(self, sample):
        return self.fn(sample) if self.fn is not None else sample


@register_preprocessing
class Lambda(Preprocessing):
    """Arbitrary callable as a stage (not serializable)."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def apply(self, sample):
        return self.fn(sample)
