"""Image transformers (the 2D OpenCV-backed set of the reference).

Parity surface: reference zoo/.../feature/image/*.scala — ImageResize,
ImageCenterCrop/ImageRandomCrop/ImageFixedCrop, ImageChannelNormalize,
ImagePixelNormalizer, ImageChannelOrder, ImageBrightness, ImageHue,
ImageSaturation, ImageColorJitter, ImageExpand, ImageFiller, ImageHFlip,
ImageBytesToMat, ImageMatToFloats, ImageMatToTensor, ImageSetToSample,
ImageRandomPreprocessing.

The reference runs these on OpenCV mats via JNI; here images are HWC float32
numpy arrays (BGR channel order by default, matching OpenCV/the reference's
pixel conventions) transformed host-side with numpy/PIL — the input
pipeline's CPU domain, feeding device transfer at the batch boundary.  Each
transform subclasses Preprocessing, so ``>>`` chains compose identically to
the reference's ``->``.
"""

from __future__ import annotations

import io
from typing import Optional, Sequence, Tuple

import numpy as np

from ..common import Preprocessing, register_preprocessing

try:  # PIL for decode/resize; the C++ loader (data/native) is the fast path
    from PIL import Image as _PILImage
    _HAS_PIL = True
except ImportError:  # pragma: no cover
    _HAS_PIL = False


class ImageFeature(dict):
    """Mutable per-image record (reference ImageFeature): holds the pixel
    array under 'image' plus metadata (uri, label, original size...)."""

    @property
    def image(self) -> np.ndarray:
        return self["image"]

    @image.setter
    def image(self, v):
        self["image"] = v


def _as_feature(sample) -> ImageFeature:
    if isinstance(sample, ImageFeature):
        return sample
    f = ImageFeature()
    if isinstance(sample, dict):
        # a plain {'image': pixels, ...} record is a feature, not pixels
        if "image" not in sample:
            raise ValueError(
                "dict sample for an image transform needs an 'image' key")
        f.update(sample)
    else:
        f["image"] = sample
    return f


class ImageProcessing(Preprocessing):
    """Base for image transforms: normalizes input to ImageFeature."""

    def apply(self, sample):
        f = _as_feature(sample)
        f["image"] = self.transform(np.asarray(f["image"]))
        return f

    def transform(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError


@register_preprocessing
class ImageBytesToMat(ImageProcessing):
    """Decode compressed bytes -> HWC float32 BGR array
    (reference ImageBytesToMat.scala / OpenCVMethod.imdecode)."""

    def apply(self, sample):
        f = _as_feature(sample)
        raw = f["image"]
        if isinstance(raw, (bytes, bytearray)):
            arr = None
            from ... import native
            if native.available():
                try:  # C++ decode (libjpeg/libpng) — the fast path
                    rgb = native.decode_image(bytes(raw))
                    arr = rgb[:, :, ::-1].astype(np.float32)  # RGB->BGR
                except ValueError:
                    arr = None  # exotic format: PIL fallback below
            if arr is None:
                if not _HAS_PIL:
                    raise RuntimeError(
                        "no decoder available (native build failed and "
                        "PIL missing)")
                img = _PILImage.open(io.BytesIO(raw)).convert("RGB")
                arr = np.asarray(img, dtype=np.float32)[:, :, ::-1]
            f["original_size"] = arr.shape
            f["image"] = arr
        return f


@register_preprocessing
class ImageResize(ImageProcessing):
    """reference ImageResize.scala."""

    def __init__(self, resize_h: int, resize_w: int):
        self.resize_h, self.resize_w = int(resize_h), int(resize_w)

    def transform(self, img):
        in_uint8_range = img.min() >= 0 and img.max() <= 255
        if _HAS_PIL and in_uint8_range and img.ndim == 3 \
                and img.shape[2] == 3:
            pil = _PILImage.fromarray(img.astype(np.uint8))
            out = pil.resize((self.resize_w, self.resize_h),
                             _PILImage.BILINEAR)
            return np.asarray(out, dtype=np.float32)
        # float-preserving path (normalized / medical images): bilinear
        # zoom per channel, no quantization
        from scipy import ndimage
        zoom = (self.resize_h / img.shape[0], self.resize_w / img.shape[1])
        if img.ndim == 3:
            zoom = zoom + (1,)
        return ndimage.zoom(img, zoom, order=1).astype(np.float32)

    def get_config(self):
        return {"resize_h": self.resize_h, "resize_w": self.resize_w}


@register_preprocessing
class BufferedImageResize(ImageResize):
    """reference BufferedImageResize.scala (same host-side resize)."""


@register_preprocessing
class ImageAspectScale(ImageProcessing):
    """Scale the short side to ``scale`` capped by ``max_size``
    (reference ImageAspectScale.scala, used by object detection)."""

    def __init__(self, scale: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.scale, self.max_size = int(scale), int(max_size)
        self.scale_multiple_of = int(scale_multiple_of)

    def transform(self, img):
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        ratio = min(self.scale / short, self.max_size / long)
        nh, nw = int(h * ratio), int(w * ratio)
        if self.scale_multiple_of > 1:
            nh = (nh // self.scale_multiple_of) * self.scale_multiple_of
            nw = (nw // self.scale_multiple_of) * self.scale_multiple_of
        return ImageResize(nh, nw).transform(img)

    def get_config(self):
        return {"scale": self.scale, "max_size": self.max_size,
                "scale_multiple_of": self.scale_multiple_of}


class _CropBase(ImageProcessing):
    def _crop(self, img, y0, x0, h, w):
        return img[y0:y0 + h, x0:x0 + w]


@register_preprocessing
class ImageCenterCrop(_CropBase):
    """reference ImageCenterCrop.scala."""

    def __init__(self, crop_height: int, crop_width: int):
        self.crop_height, self.crop_width = int(crop_height), int(crop_width)

    def transform(self, img):
        y0 = max((img.shape[0] - self.crop_height) // 2, 0)
        x0 = max((img.shape[1] - self.crop_width) // 2, 0)
        return self._crop(img, y0, x0, self.crop_height, self.crop_width)

    def get_config(self):
        return {"crop_height": self.crop_height,
                "crop_width": self.crop_width}


@register_preprocessing
class ImageRandomCrop(_CropBase):
    """reference ImageRandomCrop.scala."""

    def __init__(self, crop_height: int, crop_width: int, seed: int = 0):
        self.crop_height, self.crop_width = int(crop_height), int(crop_width)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        max_y = max(img.shape[0] - self.crop_height, 0)
        max_x = max(img.shape[1] - self.crop_width, 0)
        y0 = int(self.rng.integers(0, max_y + 1))
        x0 = int(self.rng.integers(0, max_x + 1))
        return self._crop(img, y0, x0, self.crop_height, self.crop_width)

    def get_config(self):
        return {"crop_height": self.crop_height,
                "crop_width": self.crop_width, "seed": self.seed}


@register_preprocessing
class ImageFixedCrop(_CropBase):
    """Crop by explicit bounds, normalized or pixel coords
    (reference ImageFixedCrop.scala)."""

    def __init__(self, x1, y1, x2, y2, normalized: bool = True):
        self.x1, self.y1, self.x2, self.y2 = x1, y1, x2, y2
        self.normalized = normalized

    def transform(self, img):
        h, w = img.shape[:2]
        if self.normalized:
            x1, y1 = int(self.x1 * w), int(self.y1 * h)
            x2, y2 = int(self.x2 * w), int(self.y2 * h)
        else:
            x1, y1, x2, y2 = map(int, (self.x1, self.y1, self.x2, self.y2))
        return img[y1:y2, x1:x2]

    def get_config(self):
        return {"x1": self.x1, "y1": self.y1, "x2": self.x2, "y2": self.y2,
                "normalized": self.normalized}


@register_preprocessing
class ImageChannelNormalize(ImageProcessing):
    """Subtract per-channel means, divide per-channel stds
    (reference ImageChannelNormalize.scala)."""

    def __init__(self, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0):
        # note: stored RGB-wise for API parity, applied to BGR arrays
        self.means = (mean_b, mean_g, mean_r)
        self.stds = (std_b, std_g, std_r)
        self._cfg = dict(mean_r=mean_r, mean_g=mean_g, mean_b=mean_b,
                         std_r=std_r, std_g=std_g, std_b=std_b)

    def transform(self, img):
        return ((img - np.asarray(self.means, dtype=np.float32))
                / np.asarray(self.stds, dtype=np.float32))

    def get_config(self):
        return dict(self._cfg)


@register_preprocessing
class ImagePixelNormalizer(ImageProcessing):
    """Subtract a full per-pixel mean image
    (reference ImagePixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray = None):
        self.means = np.asarray(means, dtype=np.float32)

    def transform(self, img):
        return img - self.means.reshape(img.shape)

    def get_config(self):
        return {"means": self.means.tolist()}


@register_preprocessing
class ImageChannelOrder(ImageProcessing):
    """Swap BGR <-> RGB (reference ImageChannelOrder.scala)."""

    def transform(self, img):
        return img[:, :, ::-1].copy()


@register_preprocessing
class ImageBrightness(ImageProcessing):
    """Add a random brightness delta (reference ImageBrightness.scala)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.delta_low, self.delta_high = float(delta_low), float(delta_high)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        delta = self.rng.uniform(self.delta_low, self.delta_high)
        return img + delta

    def get_config(self):
        return {"delta_low": self.delta_low, "delta_high": self.delta_high,
                "seed": self.seed}


def _bgr_to_hsv(img):
    import colorsys  # noqa: F401 - vectorized below instead
    b, g, r = img[..., 0] / 255.0, img[..., 1] / 255.0, img[..., 2] / 255.0
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    rc = np.where(delta > 0, (maxc - r) / np.maximum(delta, 1e-12), 0.0)
    gc = np.where(delta > 0, (maxc - g) / np.maximum(delta, 1e-12), 0.0)
    bc = np.where(delta > 0, (maxc - b) / np.maximum(delta, 1e-12), 0.0)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(delta == 0, 0.0, h)
    return h, s, v


def _hsv_to_bgr(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([b, g, r], axis=-1) * 255.0


@register_preprocessing
class ImageHue(ImageProcessing):
    """Random hue rotation in degrees (reference ImageHue.scala)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.delta_low, self.delta_high = float(delta_low), float(delta_high)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        delta = self.rng.uniform(self.delta_low, self.delta_high)
        h, s, v = _bgr_to_hsv(img)
        h = (h + delta / 360.0) % 1.0
        return _hsv_to_bgr(h, s, v).astype(np.float32)

    def get_config(self):
        return {"delta_low": self.delta_low, "delta_high": self.delta_high,
                "seed": self.seed}


@register_preprocessing
class ImageSaturation(ImageProcessing):
    """Random saturation scale (reference ImageSaturation.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.delta_low, self.delta_high = float(delta_low), float(delta_high)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        scale = self.rng.uniform(self.delta_low, self.delta_high)
        h, s, v = _bgr_to_hsv(img)
        s = np.clip(s * scale, 0.0, 1.0)
        return _hsv_to_bgr(h, s, v).astype(np.float32)

    def get_config(self):
        return {"delta_low": self.delta_low, "delta_high": self.delta_high,
                "seed": self.seed}


@register_preprocessing
class ImageContrast(ImageProcessing):
    """Random contrast scale (reference ImageContrast.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: int = 0):
        self.delta_low, self.delta_high = float(delta_low), float(delta_high)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        scale = self.rng.uniform(self.delta_low, self.delta_high)
        return img * scale

    def get_config(self):
        return {"delta_low": self.delta_low, "delta_high": self.delta_high,
                "seed": self.seed}


@register_preprocessing
class ImageColorJitter(ImageProcessing):
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference ImageColorJitter.scala)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self.stages = [ImageBrightness(-32, 32, seed),
                       ImageContrast(0.5, 1.5, seed),
                       ImageSaturation(0.5, 1.5, seed),
                       ImageHue(-18, 18, seed)]

    def transform(self, img):
        order = self.rng.permutation(len(self.stages))
        for i in order:
            img = self.stages[i].transform(img)
        return np.clip(img, 0, 255)

    def get_config(self):
        return {"seed": self.seed}


@register_preprocessing
class ImageExpand(ImageProcessing):
    """Randomly place the image on a larger mean-filled canvas
    (reference ImageExpand.scala)."""

    def __init__(self, means_r=123, means_g=117, means_b=104,
                 max_expand_ratio: float = 4.0, seed: int = 0):
        self.means = (means_b, means_g, means_r)
        self.max_expand_ratio = float(max_expand_ratio)
        self.rng = np.random.default_rng(seed)
        self.seed = seed
        self._cfg = dict(means_r=means_r, means_g=means_g, means_b=means_b,
                         max_expand_ratio=max_expand_ratio, seed=seed)

    def transform(self, img):
        ratio = self.rng.uniform(1.0, self.max_expand_ratio)
        h, w = img.shape[:2]
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.tile(np.asarray(self.means, dtype=np.float32),
                         (nh, nw, 1))
        y0 = int(self.rng.integers(0, nh - h + 1))
        x0 = int(self.rng.integers(0, nw - w + 1))
        canvas[y0:y0 + h, x0:x0 + w] = img
        return canvas

    def get_config(self):
        return dict(self._cfg)


@register_preprocessing
class ImageFiller(ImageProcessing):
    """Fill a normalized-coord rectangle with a value
    (reference ImageFiller.scala)."""

    def __init__(self, start_x=0.0, start_y=0.0, end_x=1.0, end_y=1.0,
                 value: int = 255):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, img):
        h, w = img.shape[:2]
        x1, y1 = int(self.box[0] * w), int(self.box[1] * h)
        x2, y2 = int(self.box[2] * w), int(self.box[3] * h)
        out = img.copy()
        out[y1:y2, x1:x2] = self.value
        return out

    def get_config(self):
        return {"start_x": self.box[0], "start_y": self.box[1],
                "end_x": self.box[2], "end_y": self.box[3],
                "value": self.value}


@register_preprocessing
class ImageHFlip(ImageProcessing):
    """Horizontal flip, optionally random (reference ImageHFlip.scala)."""

    def __init__(self, probability: float = 1.0, seed: int = 0):
        self.probability = float(probability)
        self.rng = np.random.default_rng(seed)
        self.seed = seed

    def transform(self, img):
        if self.rng.uniform() <= self.probability:
            return img[:, ::-1].copy()
        return img

    def get_config(self):
        return {"probability": self.probability, "seed": self.seed}


@register_preprocessing
class ImageRandomPreprocessing(Preprocessing):
    """Apply an inner transform with probability p
    (reference ImageRandomPreprocessing.scala)."""

    def __init__(self, preprocessing: Preprocessing, prob: float,
                 seed: int = 0):
        self.preprocessing = preprocessing
        self.prob = float(prob)
        self.rng = np.random.default_rng(seed)

    def apply(self, sample):
        if self.rng.uniform() <= self.prob:
            return self.preprocessing.apply(sample)
        return _as_feature(sample)


@register_preprocessing
class ImageMatToFloats(ImageProcessing):
    """Mat -> float array (identity here: arrays are already floats;
    reference ImageMatToFloats.scala)."""

    def transform(self, img):
        return np.asarray(img, dtype=np.float32)


@register_preprocessing
class ImageMatToTensor(Preprocessing):
    """ImageFeature -> tensor under 'tensor', NHWC or NCHW
    (reference ImageMatToTensor.scala; the reference emits CHW for BigDL,
    the TPU default is HWC)."""

    def __init__(self, format: str = "NHWC"):  # noqa: A002
        self.format = format

    def apply(self, sample):
        f = _as_feature(sample)
        img = np.asarray(f["image"], dtype=np.float32)
        if self.format.upper() == "NCHW":
            img = np.transpose(img, (2, 0, 1))
        f["tensor"] = img
        return f

    def get_config(self):
        return {"format": self.format}


@register_preprocessing
class ImageSetToSample(Preprocessing):
    """ImageFeature -> (x, y) sample from selected keys
    (reference ImageSetToSample.scala)."""

    def __init__(self, input_keys=("tensor",), target_keys=("label",)):
        self.input_keys = tuple(input_keys)
        self.target_keys = tuple(target_keys)

    def apply(self, sample):
        f = _as_feature(sample)
        xs = [np.asarray(f[k]) for k in self.input_keys if k in f]
        ys = [np.asarray(f[k]) for k in self.target_keys
              if k in f and f[k] is not None]
        x = xs[0] if len(xs) == 1 else tuple(xs)
        y = (ys[0] if len(ys) == 1 else tuple(ys)) if ys else None
        return (x, y)

    def get_config(self):
        return {"input_keys": list(self.input_keys),
                "target_keys": list(self.target_keys)}


@register_preprocessing
class ImageRandomAspectScale(ImageProcessing):
    """Aspect-preserving resize with the target short side chosen
    randomly from ``scales`` per image (reference
    imagePreprocessing.py:199 — detection train-time multi-scale)."""

    def __init__(self, scales, scale_multiple_of: int = 1,
                 max_size: int = 1000, seed: int = None):
        self.scales = [int(s) for s in scales]
        self.scale_multiple_of = int(scale_multiple_of)
        self.max_size = int(max_size)
        self.seed = seed
        self._rng = np.random.RandomState(seed)

    def transform(self, img):
        scale = self.scales[self._rng.randint(len(self.scales))]
        return ImageAspectScale(
            scale, max_size=self.max_size,
            scale_multiple_of=self.scale_multiple_of).transform(img)

    def get_config(self):
        return {"scales": list(self.scales),
                "scale_multiple_of": self.scale_multiple_of,
                "max_size": self.max_size, "seed": self.seed}


# reference-name aliases (imagePreprocessing.py vocabulary)
ImagePreprocessing = ImageProcessing
ImagePixelNormalize = ImagePixelNormalizer
ImageFeatureToTensor = ImageMatToTensor
RowToImageFeature = ImageBytesToMat
