from .imageset import ImageSet, LocalImageSet, DistributedImageSet
from .transforms import (
    ImageFeature, ImageProcessing, ImageBytesToMat, ImageResize,
    BufferedImageResize, ImageAspectScale, ImageCenterCrop, ImageRandomCrop,
    ImageFixedCrop, ImageChannelNormalize, ImagePixelNormalizer,
    ImageChannelOrder, ImageBrightness, ImageHue, ImageSaturation,
    ImageContrast, ImageColorJitter, ImageExpand, ImageFiller, ImageHFlip,
    ImageRandomPreprocessing, ImageMatToFloats, ImageMatToTensor,
    ImageSetToSample, ImageRandomAspectScale, ImagePreprocessing,
    ImagePixelNormalize, ImageFeatureToTensor, RowToImageFeature)
