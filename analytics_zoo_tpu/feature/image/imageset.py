"""ImageSet: collections of images flowing through transform chains.

Parity surface: reference zoo/.../feature/image/ImageSet.scala:32-170 —
LocalImageSet/DistributedImageSet, ``read`` from paths, ``transform``,
bridge to the training DataSet.  The reference's "distributed" variant is an
RDD of ImageFeatures; on TPU the analogue is a per-host collection feeding
the device mesh (SURVEY §2.9: input distribution is the one Spark role that
becomes per-host pipelines), so LocalImageSet covers both roles per host.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional, Sequence

import numpy as np

from ..common import Preprocessing
from .transforms import ImageBytesToMat, ImageFeature


class ImageSet:
    """A set of ImageFeatures + the transform API."""

    def __init__(self, features: Sequence[ImageFeature]):
        self.features: List[ImageFeature] = list(features)
        self.predictions: Optional[np.ndarray] = None

    # ---- constructors (ImageSet.read parity, ImageSet.scala:80-117) ----
    @classmethod
    def read(cls, path: str, with_label: bool = False,
             one_based_label: bool = True) -> "ImageSet":
        """Read images from a file/dir/glob.  With ``with_label``, each
        immediate subdirectory name becomes a class label (the layout the
        reference's finetune examples use)."""
        if os.path.isfile(path):
            paths = [path]
        elif os.path.isdir(path):
            paths = sorted(
                p for p in glob.glob(os.path.join(path, "**", "*"),
                                     recursive=True) if os.path.isfile(p))
        else:
            paths = sorted(glob.glob(path))
        label_map = {}
        feats = []
        for p in paths:
            f = ImageFeature()
            with open(p, "rb") as fh:
                f["image"] = fh.read()
            f["uri"] = p
            if with_label:
                cls_name = os.path.basename(os.path.dirname(p))
                if cls_name not in label_map:
                    label_map[cls_name] = len(label_map) + (
                        1 if one_based_label else 0)
                f["label"] = np.asarray([label_map[cls_name]],
                                        dtype=np.float32)
            feats.append(f)
        out = cls(feats)
        out.label_map = label_map
        # decode eagerly so downstream transforms see arrays
        return out.transform(ImageBytesToMat())

    @classmethod
    def from_arrays(cls, images: np.ndarray,
                    labels: Optional[np.ndarray] = None) -> "ImageSet":
        feats = []
        for i, img in enumerate(images):
            f = ImageFeature()
            f["image"] = np.asarray(img, dtype=np.float32)
            if labels is not None:
                f["label"] = np.asarray(labels[i])
            feats.append(f)
        return cls(feats)

    # ---- transform (ImageSet.scala:99) ----
    def transform(self, transformer: Preprocessing) -> "ImageSet":
        self.features = [transformer.apply(f) for f in self.features]
        return self

    def copy(self) -> "ImageSet":
        """Shallow-copy the set with COPIED feature dicts: transforms on
        the copy reassign keys on the new dicts, so the original set's
        images survive (arrays are shared until a transform replaces
        them, never mutated in place).  Preserves the concrete class and
        set-level attributes (predictions, label_map, ...)."""
        new = type(self)([type(f)(f) for f in self.features])
        for k, v in self.__dict__.items():
            if k != "features":
                setattr(new, k, v)
        return new

    # sugar matching the reference's ``imageset -> transformer``
    def __rshift__(self, transformer: Preprocessing) -> "ImageSet":
        return self.transform(transformer)

    # ---- bridges ----
    def to_array(self, key: str = None) -> np.ndarray:
        """Stack into one batch array (tensor key if materialized)."""
        key = key or ("tensor" if self.features
                      and "tensor" in self.features[0] else "image")
        return np.stack([np.asarray(f[key], dtype=np.float32)
                         for f in self.features])

    def labels(self) -> Optional[np.ndarray]:
        if not self.features or "label" not in self.features[0]:
            return None
        return np.stack([np.asarray(f["label"]) for f in self.features])

    def to_dataset(self):
        """Bridge to the training Dataset (the reference's
        ImageSet→DataSet conversion, ImageSet.scala:130-170)."""
        from ...data.dataset import Dataset
        return Dataset.from_ndarray(self.to_array(), self.labels())

    def set_predictions(self, preds):
        if (isinstance(preds, list) and preds
                and isinstance(preds[0], (list, tuple)) and preds[0]
                and isinstance(preds[0][0], tuple)):
            # structured per-image results — label_output's
            # [(label, confidence), ...] lists: keep python objects,
            # np.asarray would stringify the mixed types.  Plain numeric
            # list-of-lists still becomes an ndarray below.
            self.predictions = list(preds)
        else:
            self.predictions = np.asarray(preds)
        for f, p in zip(self.features, self.predictions):
            f["predict"] = p

    def get_predicts(self):
        """Parity: ImageSet.getPredicts — list of (uri, prediction)."""
        return [(f.get("uri"), f.get("predict")) for f in self.features]

    def __len__(self):
        return len(self.features)


class LocalImageSet(ImageSet):
    """Alias matching the reference's Local/Distributed split; per-host
    collections are the TPU-native distribution unit."""


DistributedImageSet = LocalImageSet
