from .common import (Preprocessing, ChainedPreprocessing, SeqToTensor,
                     ArrayToTensor, ScalarToTensor, MLlibVectorToTensor,
                     TensorToSample, FeatureLabelPreprocessing,
                     FeatureToTupleAdapter, BigDLAdapter, ToTuple)
