from .common import ZooModel, register_zoo_model
from .textclassification import TextClassifier
from .textgeneration import TransformerLM
from .recommendation import (Recommender, NeuralCF, WideAndDeep,
                             UserItemFeature, UserItemPrediction,
                             ColumnFeatureInfo)
from .recommendation_utils import (hash_bucket, categorical_from_vocab_list,
                                   get_boundaries, get_negative_samples,
                                   get_wide_tensor, get_deep_tensor,
                                   row_to_feature, row_to_sample,
                                   to_user_item_feature,
                                   features_to_arrays)
from .image.classification import ImageClassifier, resnet50, label_output
from .image.detection import (ObjectDetector, ssd_vgg16, ssd_mobilenet,
                              decode_output, ScaleDetection, visualize,
                              Visualizer)
from .image.config import (ImageConfigure, PaddingParam, read_label_map,
                           read_imagenet_label_map, read_pascal_label_map,
                           read_coco_label_map)
