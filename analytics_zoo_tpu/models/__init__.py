from .common import ZooModel, register_zoo_model
from .textclassification import TextClassifier
from .recommendation import (Recommender, NeuralCF, WideAndDeep,
                             UserItemFeature, UserItemPrediction,
                             ColumnFeatureInfo)
from .image.classification import ImageClassifier, resnet50, label_output
from .image.detection import (ObjectDetector, ssd_vgg16, ssd_mobilenet,
                              decode_output, ScaleDetection, visualize)
