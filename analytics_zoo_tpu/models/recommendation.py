"""Recommendation models: Recommender base, NeuralCF, WideAndDeep.

Parity surface: reference zoo/.../models/recommendation/
{Recommender.scala:36-96, NeuralCF.scala:43-95, WideAndDeep.scala:80-165,
Utils.scala}.  The graph structure follows the reference exactly (MLP +
optional MF branch fused by concat; wide sparse-linear + deep tower fused by
add + log-softmax); lookups are jnp gathers, the towers are MXU matmuls.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..pipeline.api import autograd as A
from ..pipeline.api.keras.engine import Model
from ..pipeline.api.keras.layers import Dense, Embedding
from ..core.graph import Input
from .common import ZooModel, register_zoo_model


@dataclasses.dataclass
class UserItemFeature:
    """Parity: reference UserItemFeature (user id, item id, sample)."""

    user_id: int
    item_id: int
    feature: object  # model input (np array / tuple)
    label: Optional[int] = None


@dataclasses.dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


@dataclasses.dataclass
class ColumnFeatureInfo:
    """Parity: reference ColumnFeatureInfo (WideAndDeep.scala:38-78)."""

    wide_base_cols: Sequence[str] = ()
    wide_base_dims: Sequence[int] = ()
    wide_cross_cols: Sequence[str] = ()
    wide_cross_dims: Sequence[int] = ()
    indicator_cols: Sequence[str] = ()
    indicator_dims: Sequence[int] = ()
    embed_cols: Sequence[str] = ()
    embed_in_dims: Sequence[int] = ()
    embed_out_dims: Sequence[int] = ()
    continuous_cols: Sequence[str] = ()
    label: str = "label"


class Recommender(ZooModel):
    """recommendForUser / recommendForItem / predictUserItemPair
    (reference Recommender.scala:36-96)."""

    def predict_user_item_pair(self, feature_pairs: Sequence[UserItemFeature],
                               batch_size: int = 128
                               ) -> List[UserItemPrediction]:
        feats = [p.feature for p in feature_pairs]
        x = (tuple(np.stack([f[i] for f in feats])
                   for i in range(len(feats[0])))
             if isinstance(feats[0], (tuple, list)) else np.stack(feats))
        probs = np.asarray(self.predict(x, batch_size=batch_size))
        # model emits log-probabilities (log-softmax, reference parity)
        probs = np.exp(probs)
        preds = np.argmax(probs, axis=-1)
        return [
            UserItemPrediction(p.user_id, p.item_id, int(c) + 1,
                               float(pr[c]))
            for p, c, pr in zip(feature_pairs, preds, probs)]

    def recommend_for_user(self, feature_pairs: Sequence[UserItemFeature],
                           max_items: int) -> List[UserItemPrediction]:
        preds = self.predict_user_item_pair(feature_pairs)
        by_user = {}
        for pred in preds:
            by_user.setdefault(pred.user_id, []).append(pred)
        out = []
        for user, items in by_user.items():
            items.sort(key=lambda r: -r.probability)
            out.extend(items[:max_items])
        return out

    def recommend_for_item(self, feature_pairs: Sequence[UserItemFeature],
                           max_users: int) -> List[UserItemPrediction]:
        preds = self.predict_user_item_pair(feature_pairs)
        by_item = {}
        for pred in preds:
            by_item.setdefault(pred.item_id, []).append(pred)
        out = []
        for item, users in by_item.items():
            users.sort(key=lambda r: -r.probability)
            out.extend(users[:max_users])
        return out


@register_zoo_model
class NeuralCF(Recommender):
    """Neural Collaborative Filtering (reference NeuralCF.scala:43-95).

    Input: int tensor (batch, 2) of 1-based [user_id, item_id].
    Output: log-softmax over num_classes.
    """

    def __init__(self, user_count=None, item_count=None, num_classes=None,
                 user_embed=20, item_embed=20, hidden_layers=(40, 20, 10),
                 include_mf=True, mf_embed=20, name=None, **kw):
        super().__init__(name=name, user_count=user_count,
                         item_count=item_count, num_classes=num_classes,
                         user_embed=user_embed, item_embed=item_embed,
                         hidden_layers=tuple(hidden_layers),
                         include_mf=include_mf, mf_embed=mf_embed, **kw)

    def build_model(self) -> Model:
        h = self.hyper
        pair = Input((2,), name="pair_input")
        user = pair.index_select(1, 0)  # (batch,)
        item = pair.index_select(1, 1)
        # +1: ids are 1-based (reference LookupTable semantics)
        mlp_user = Embedding(h["user_count"] + 1, h["user_embed"],
                             init="normal")(user)
        mlp_item = Embedding(h["item_count"] + 1, h["item_embed"],
                             init="normal")(item)
        merged = A.concat([mlp_user, mlp_item], axis=-1)
        for width in h["hidden_layers"]:
            merged = Dense(width, activation="relu")(merged)
        if h["include_mf"]:
            if h["mf_embed"] <= 0:
                raise ValueError(
                    "please provide meaningful number of embedding units")
            mf_user = Embedding(h["user_count"] + 1, h["mf_embed"],
                                init="normal")(user)
            mf_item = Embedding(h["item_count"] + 1, h["mf_embed"],
                                init="normal")(item)
            mf = mf_user * mf_item
            merged = A.concat([mf, merged], axis=-1)
        logits = Dense(h["num_classes"])(merged)
        from ..pipeline.api.keras.layers import Activation
        log_probs = Activation("log_softmax")(logits)
        return Model(input=pair, output=log_probs,
                     name="net")


@register_zoo_model
class WideAndDeep(Recommender):
    """Wide & Deep (reference WideAndDeep.scala:80-165).

    Inputs (matching the reference's assembled tensors, Utils.scala
    getWide/getDeep):
      wide input  — int ids (batch, n_wide_cols), each id pre-offset into
                    the concatenated wide dimension space (base + cross);
      deep input  — floats (batch, indicator_width + n_embed_cols +
                    n_continuous): multi-hot indicators, then embed ids,
                    then continuous values.
    Output: log-softmax over num_classes.
    """

    def __init__(self, model_type="wide_n_deep", num_classes=None,
                 column_info: Optional[ColumnFeatureInfo] = None,
                 hidden_layers=(40, 20, 10), name=None, **kw):
        if column_info is not None:
            # flatten ColumnFeatureInfo into plain hypers so get_config /
            # from_config round-trips without the dataclass
            ci = (ColumnFeatureInfo(**column_info)
                  if isinstance(column_info, dict) else column_info)
            kw.update(
                wide_base_dims=tuple(ci.wide_base_dims),
                wide_cross_dims=tuple(ci.wide_cross_dims),
                indicator_dims=tuple(ci.indicator_dims),
                embed_in_dims=tuple(ci.embed_in_dims),
                embed_out_dims=tuple(ci.embed_out_dims),
                n_continuous=len(ci.continuous_cols))
        kw.setdefault("wide_base_dims", ())
        kw.setdefault("wide_cross_dims", ())
        kw.setdefault("indicator_dims", ())
        kw.setdefault("embed_in_dims", ())
        kw.setdefault("embed_out_dims", ())
        kw.setdefault("n_continuous", 0)
        kw = {k: (tuple(v) if isinstance(v, list) else v)
              for k, v in kw.items()}
        super().__init__(
            name=name, model_type=model_type, num_classes=num_classes,
            hidden_layers=tuple(hidden_layers), **kw)

    def build_model(self) -> Model:
        h = self.hyper
        num_classes = h["num_classes"]
        model_type = h["model_type"]
        wide_total = sum(h["wide_base_dims"]) + sum(h["wide_cross_dims"])
        n_wide_cols = len(h["wide_base_dims"]) + len(h["wide_cross_dims"])
        indicator_width = sum(h["indicator_dims"])
        n_embed = len(h["embed_in_dims"])
        n_cont = h["n_continuous"]

        inputs, wide_out, deep_out = [], None, None

        if model_type in ("wide", "wide_n_deep"):
            wide_in = Input((n_wide_cols,), name="wide_input")
            inputs.append(wide_in)
            # sparse linear: sum one-hot(id) @ W == sum of embedding rows
            # (reference LookupTableSparse init Zeros + CAdd bias)
            wide_embed = Embedding(wide_total + 1, num_classes,
                                   init="zero")(wide_in)
            wide_sum = A.sum(wide_embed, axis=1)  # (batch, num_classes)
            bias = A.Parameter((num_classes,), init_method="zero",
                               name="wide_bias")
            wide_out = wide_sum + bias

        if model_type in ("deep", "wide_n_deep"):
            deep_width = indicator_width + n_embed + n_cont
            deep_in = Input((deep_width,), name="deep_input")
            inputs.append(deep_in)
            parts = []
            if indicator_width:
                parts.append(deep_in.slice(1, 0, indicator_width))
            for i, (in_dim, out_dim) in enumerate(
                    zip(h["embed_in_dims"], h["embed_out_dims"])):
                ids = deep_in.index_select(1, indicator_width + i)
                parts.append(Embedding(in_dim + 1, out_dim,
                                       init="normal")(ids))
            if n_cont:
                parts.append(deep_in.slice(
                    1, indicator_width + n_embed, n_cont))
            deep = parts[0] if len(parts) == 1 else A.concat(parts, axis=-1)
            for width in h["hidden_layers"]:
                deep = Dense(width, activation="relu")(deep)
            deep_out = Dense(num_classes)(deep)

        if model_type == "wide_n_deep":
            logits = wide_out + deep_out
        elif model_type == "wide":
            logits = wide_out
        elif model_type == "deep":
            logits = deep_out
        else:
            raise ValueError(f"unknown type {model_type!r}")
        from ..pipeline.api.keras.layers import Activation
        out = Activation("log_softmax")(logits)
        return Model(input=inputs if len(inputs) > 1 else inputs[0],
                     output=out, name="net")
