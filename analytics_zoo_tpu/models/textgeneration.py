"""TransformerLM — the long-context flagship of the model zoo.

The reference has no transformer anywhere (SURVEY §5); the task brief
makes long-context a first-class requirement, so the zoo gets a
decoder-only LM assembled entirely from the framework's own layers:
``Embedding`` + ``PositionalEmbedding`` → pre-norm blocks of
``MultiHeadSelfAttention`` (causal, pallas flash kernel on TPU, the
transpose-free bhsd projection path) and a gelu MLP, with ``Merge``
residuals — a log-softmax head trained with ``class_nll`` on
next-token targets.

Scaling story: the attention is the same kernel `parallel/
ring_attention` shards over a ``seq`` mesh axis; tensor/fsdp
strategies shard the Dense/attention matmuls via ``compile(
strategy=...)`` like every other zoo model.
"""

from __future__ import annotations

from ..pipeline.api.keras.engine import Model
from ..pipeline.api.keras.layers import (
    Activation, Dense, Dropout, Embedding, Input, LayerNorm, Merge,
    MultiHeadSelfAttention, PositionalEmbedding, SwitchMoE)
from .common import ZooModel, register_zoo_model


@register_zoo_model
class TransformerLM(ZooModel):
    """Decoder-only transformer language model.

    Args:
        vocab_size: token vocabulary.
        seq_len: training sequence length (positions beyond ``max_len``
            raise; ``max_len`` defaults to ``seq_len``).
        n_layers / d_model / n_heads / d_ff: the usual dials
            (``d_ff`` defaults to ``4 * d_model``).
        dropout: residual-path dropout probability.
        implementation: attention implementation forwarded to
            :class:`MultiHeadSelfAttention` (incl. ``"ring"`` for
            sequence parallelism over a ``seq`` mesh axis).
        moe_every: replace every k-th MLP with a :class:`SwitchMoE`
            FFN (``n_experts`` experts, pre-norm, router aux loss
            auto-wired — the Switch-transformer shape).  ``None``
            (default) keeps all-dense MLPs.  The layer runs the
            single-device formulation (expert params replicated under
            data parallelism); explicit expert-axis sharding is
            ``parallel.moe_sharded``.

    Output: (batch, seq_len, vocab_size) LOG-probabilities — compile
    with ``loss="class_nll"`` and next-token int targets of shape
    (batch, seq_len).
    """

    def __init__(self, vocab_size=None, seq_len=128, n_layers=2,
                 d_model=128, n_heads=4, d_ff=None, max_len=None,
                 dropout=0.0, implementation="auto", moe_every=None,
                 n_experts=8, capacity_factor=1.25, remat=False,
                 name=None, **kw):
        super().__init__(
            name=name, vocab_size=vocab_size, seq_len=seq_len,
            n_layers=n_layers, d_model=d_model, n_heads=n_heads,
            d_ff=d_ff or 4 * d_model, max_len=max_len or seq_len,
            dropout=dropout, implementation=implementation,
            moe_every=moe_every, n_experts=n_experts,
            capacity_factor=capacity_factor, remat=remat, **kw)

    def build_model(self) -> Model:
        h = self.hyper
        tokens = Input(shape=(h["seq_len"],), name="tokens")
        # explicit names: the KV-cache decode path (generation.py) reads
        # these params by layer name
        x = Embedding(h["vocab_size"], h["d_model"],
                      input_length=h["seq_len"],
                      name="tok_embed")(tokens)
        x = PositionalEmbedding(h["max_len"], name="pos_embed")(x)
        remat = bool(h.get("remat"))
        for i in range(h["n_layers"]):
            a = LayerNorm(name=f"ln_attn_{i}")(x)
            attn = MultiHeadSelfAttention(
                h["n_heads"], causal=True,
                implementation=h["implementation"],
                name=f"attn_{i}")
            # remat the activation-heavy sublayers (attention, and the
            # MLP's up/down pair as two regions): their INTERNALS
            # recompute in the backward pass.  Region boundaries are
            # still saved — including the d_ff-wide gelu output between
            # mlp_up and mlp_down — so per-block saved memory is the
            # residual stream plus one d_ff activation, not zero;
            # measured net effect 17.8x fewer saved bytes at seq 1024
            # (tests/test_remat.py)
            attn.remat = remat
            a = attn(a)
            if h["dropout"]:
                a = Dropout(h["dropout"])(a)
            x = Merge(mode="sum")([x, a])
            moe = (h["moe_every"]
                   and (i + 1) % h["moe_every"] == 0)
            f = LayerNorm(name=f"ln_mlp_{i}")(x)
            if moe:
                # pre-norm MoE sublayer, composed exactly like the
                # dense MLP (Switch Transformer applies LN before the
                # MoE FFN); aux loss auto-wired through layer state
                moe_layer = SwitchMoE(
                    n_experts=h["n_experts"],
                    hidden_dim=h["d_ff"], residual=False,
                    capacity_factor=h.get("capacity_factor", 1.25),
                    name=f"moe_{i}")
                moe_layer.remat = remat
                f = moe_layer(f)
            else:
                up = Dense(h["d_ff"], activation="gelu",
                           name=f"mlp_up_{i}")
                down = Dense(h["d_model"], name=f"mlp_down_{i}")
                up.remat = down.remat = remat
                f = down(up(f))
            if h["dropout"]:
                f = Dropout(h["dropout"])(f)
            x = Merge(mode="sum")([x, f])
        x = LayerNorm(name="ln_final")(x)
        logits = Dense(h["vocab_size"], name="lm_head")(x)
        out = Activation("log_softmax")(logits)
        return Model(input=tokens, output=out, name="transformer_lm")

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 seed: int = 0, num_beams: int = 1,
                 prompt_lengths=None):
        """Autoregressive continuation from a KV cache — greedy
        (``temperature=0``), temperature/top-k/top-p sampling, or beam
        search (``num_beams > 1``); ragged right-padded prompts decode
        from their own ``prompt_lengths``.  The whole decode runs as
        ONE compiled scan.  See
        :func:`analytics_zoo_tpu.models.generation.generate`."""
        from .generation import generate
        return generate(self, prompt_ids, max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, seed=seed, num_beams=num_beams,
                        prompt_lengths=prompt_lengths)
