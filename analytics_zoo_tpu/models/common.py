"""ZooModel: base class of the built-in model zoo.

Parity surface: reference zoo/.../models/common/ZooModel.scala:38-146 —
``buildModel()`` defines the network, plus saveModel/loadModel,
predictClasses and summary, all delegated to the wrapped KerasNet here.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..core.module import name_scope
from ..pipeline.api.keras.engine import KerasNet, _MODEL_CLASSES


class ZooModel(KerasNet):
    """A predefined model whose network comes from ``build_model()``."""

    def __init__(self, name=None, **hyper):
        super().__init__(name=name)
        self.hyper = hyper
        # deterministic inner-layer names: weights saved from this model
        # restore into a rebuild in any process (see name_scope docstring)
        with name_scope(type(self).__name__.lower()):
            self.model = self.build_model()

    def build_model(self) -> KerasNet:
        raise NotImplementedError

    def to_graph(self):
        return self.model.to_graph()

    # persistence: hyperparameters + weights
    def get_config(self):
        return {"name": self.name, "hyper": self.hyper,
                "compile_args": self._compile_args}

    @classmethod
    def from_config(cls, config):
        m = cls(name=config.get("name"), **config["hyper"])
        m._compile_args = config.get("compile_args")
        return m

def parse_quantize_name(model_name: str):
    """'<arch>[-quantize]' -> (arch, wants_int8) — the one place the
    registry's quantize-suffix convention is encoded (reference carries
    '*-quantize' variants, ObjectDetectionConfig.scala:33-44,
    ImageClassificationConfig.scala:34-50)."""
    if model_name.endswith("-quantize"):
        return model_name[:-len("-quantize")], True
    return model_name, False


class QuantizedVariantMixin:
    """Shared machinery for zoo models whose registry carries
    '<name>-quantize' variants: lazy int8 graph on predict, invalidated
    by EVERY weight-mutating entry point so a quantized handle can never
    serve stale weights."""

    _quantized_net = None

    def _invalidate_quantized(self):
        self._quantized_net = None

    def compile(self, *a, **kw):
        self._invalidate_quantized()
        return super().compile(*a, **kw)

    def fit(self, *a, **kw):
        self._invalidate_quantized()
        return super().fit(*a, **kw)

    def set_weights(self, params):
        self._invalidate_quantized()
        return super().set_weights(params)

    def load_weights(self, directory: str, tag=None):
        self._invalidate_quantized()
        return super().load_weights(directory, tag)

    def transfer_weights_from(self, other):
        self._invalidate_quantized()
        return super().transfer_weights_from(other)

    def predict(self, x, batch_size: int = 32, distributed: bool = True):
        """'-quantize' variants run int8 inference; the int8 graph is
        built lazily from the current weights."""
        _, wants_int8 = parse_quantize_name(self.hyper["model_name"])
        if wants_int8:
            if self._quantized_net is None:
                self._quantized_net = self.quantize()
            return self._quantized_net.predict(x, batch_size)
        return super().predict(x, batch_size, distributed)


def register_zoo_model(cls):
    """Make the model loadable via KerasNet.load_model."""
    _MODEL_CLASSES[cls.__name__] = cls
    return cls
