"""ZooModel: base class of the built-in model zoo.

Parity surface: reference zoo/.../models/common/ZooModel.scala:38-146 —
``buildModel()`` defines the network, plus saveModel/loadModel,
predictClasses and summary, all delegated to the wrapped KerasNet here.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..core.module import name_scope
from ..pipeline.api.keras.engine import KerasNet, _MODEL_CLASSES


class ZooModel(KerasNet):
    """A predefined model whose network comes from ``build_model()``."""

    def __init__(self, name=None, **hyper):
        super().__init__(name=name)
        self.hyper = hyper
        # deterministic inner-layer names: weights saved from this model
        # restore into a rebuild in any process (see name_scope docstring)
        with name_scope(type(self).__name__.lower()):
            self.model = self.build_model()

    def build_model(self) -> KerasNet:
        raise NotImplementedError

    def to_graph(self):
        return self.model.to_graph()

    # persistence: hyperparameters + weights
    def get_config(self):
        return {"name": self.name, "hyper": self.hyper,
                "compile_args": self._compile_args}

    @classmethod
    def from_config(cls, config):
        m = cls(name=config.get("name"), **config["hyper"])
        m._compile_args = config.get("compile_args")
        return m

def register_zoo_model(cls):
    """Make the model loadable via KerasNet.load_model."""
    _MODEL_CLASSES[cls.__name__] = cls
    return cls
