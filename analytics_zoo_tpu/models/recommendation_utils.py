"""Feature-assembly helpers for the recommendation models.

Parity surface: reference ``pyzoo/zoo/models/recommendation/utils.py``
(hash_bucket :24, categorical_from_vocab_list :28, get_boundaries :35,
get_negative_samples :45, get_wide_tensor :49, get_deep_tensor :67,
row_to_sample :88, to_user_item_feature :104).  The reference emits
BigDL ``JTensor.sparse`` wide tensors; our ``WideAndDeep`` consumes the
equivalent dense form — a vector of ids pre-offset into the
concatenated wide dimension space (one id per wide column), which the
model turns into a sparse-linear lookup (``Embedding`` row-sum).

``row`` below is any mapping from column name to value (a plain dict or
a ``pandas`` Series).
"""

from typing import Dict, List, Optional, Sequence, Tuple
import zlib

import numpy as np

from .recommendation import ColumnFeatureInfo, UserItemFeature


def hash_bucket(content, bucket_size: int = 1000, start: int = 0) -> int:
    """Stable string hash into ``bucket_size`` buckets.

    Unlike the reference (python ``hash``, randomized per process since
    PEP 456), this uses crc32 so feature ids are reproducible across
    runs — required for checkpoint/resume to see the same vocabulary.
    """
    h = zlib.crc32(str(content).encode("utf-8"))
    return h % bucket_size + start


def categorical_from_vocab_list(value, vocab_list: Sequence,
                                default: int = -1, start: int = 0) -> int:
    try:
        return list(vocab_list).index(value) + start
    except ValueError:
        return default + start


def get_boundaries(target, boundaries: Sequence[float],
                   default: int = -1, start: int = 0) -> int:
    if target == "?":
        return default + start
    for i, b in enumerate(boundaries):
        if target < b:
            return i + start
    return len(boundaries) + start


def get_negative_samples(indexed: Sequence[Tuple[int, int]],
                         item_count: Optional[int] = None,
                         neg_per_pos: int = 1,
                         seed: int = 0) -> List[Tuple[int, int]]:
    """Sample (user, item) pairs the user has NOT interacted with.

    Reference delegates to BigDL ``getNegativeSamples``; here it is a
    pure-numpy implementation: for each positive (user, item) pair draw
    ``neg_per_pos`` items uniformly from the items outside the user's
    positive set.  Ids are 1-based, matching the models' LookupTable
    semantics.
    """
    pos_by_user: Dict[int, set] = {}
    for u, i in indexed:
        pos_by_user.setdefault(int(u), set()).add(int(i))
    if item_count is None:
        item_count = max(i for _, i in indexed)
    rs = np.random.RandomState(seed)
    out: List[Tuple[int, int]] = []
    for u, i in indexed:
        pos = pos_by_user[int(u)]
        if len(pos) >= item_count:
            continue
        for _ in range(neg_per_pos):
            j = int(rs.randint(1, item_count + 1))
            while j in pos:
                j = int(rs.randint(1, item_count + 1))
            out.append((int(u), j))
    return out


def get_wide_tensor(row, column_info: ColumnFeatureInfo) -> np.ndarray:
    """Offset each wide column's id into the concatenated wide space.

    Raises on ids outside [0, dim) — an out-of-range id (e.g. the -1 an
    unhandled OOV default produces) would otherwise silently land in an
    adjacent column's bucket range.
    """
    cols = list(column_info.wide_base_cols) + list(column_info.wide_cross_cols)
    dims = list(column_info.wide_base_dims) + list(column_info.wide_cross_dims)
    ids, acc = [], 0
    for i, col in enumerate(cols):
        if i > 0:
            acc += dims[i - 1]
        v = int(row[col])
        if not 0 <= v < dims[i]:
            raise ValueError(
                f"wide column {col!r}: id {v} outside [0, {dims[i]}) — "
                f"reserve an OOV bucket (e.g. default=0, start=1 with "
                f"dim+1) instead of letting unknowns go negative")
        ids.append(acc + v)
    return np.asarray(ids, dtype=np.int32)


def get_deep_tensor(row, column_info: ColumnFeatureInfo) -> np.ndarray:
    """Multi-hot indicators, then raw embed ids, then continuous values."""
    ind_cols = list(column_info.indicator_cols)
    ind_dims = list(column_info.indicator_dims)
    tail_cols = list(column_info.embed_cols) + list(column_info.continuous_cols)
    width = sum(ind_dims) + len(tail_cols)
    deep = np.zeros((width,), dtype=np.float32)
    acc = 0
    for i, col in enumerate(ind_cols):
        if i > 0:
            acc += ind_dims[i - 1]
        val = row[col]
        for v in (val if isinstance(val, (list, tuple, set, np.ndarray))
                  else (val,)):
            v = int(v)
            if not 0 <= v < ind_dims[i]:
                raise ValueError(
                    f"indicator column {col!r}: id {v} outside "
                    f"[0, {ind_dims[i]}) — would corrupt a neighboring "
                    f"feature slot; reserve an OOV bucket instead")
            deep[acc + v] = 1.0
    for i, col in enumerate(tail_cols):
        deep[sum(ind_dims) + i] = float(row[col])
    return deep


def row_to_feature(row, column_info: ColumnFeatureInfo,
                   model_type: str = "wide_n_deep"):
    """Assemble the model input for one row (reference row_to_sample)."""
    model_type = model_type.lower()
    if model_type == "wide_n_deep":
        return (get_wide_tensor(row, column_info),
                get_deep_tensor(row, column_info))
    if model_type == "wide":
        return (get_wide_tensor(row, column_info),)
    if model_type == "deep":
        return (get_deep_tensor(row, column_info),)
    raise TypeError("Unsupported model_type: %s" % model_type)


def to_user_item_feature(row, column_info: ColumnFeatureInfo,
                         model_type: str = "wide_n_deep") -> UserItemFeature:
    try:
        label = row[column_info.label]
    except (KeyError, IndexError):
        label = None
    return UserItemFeature(int(row["userId"]), int(row["itemId"]),
                           row_to_feature(row, column_info, model_type),
                           label=None if label is None else int(label))


def features_to_arrays(pairs: Sequence[UserItemFeature]):
    """Stack a list of UserItemFeatures into model-input arrays + labels."""
    first = pairs[0].feature
    n_parts = len(first) if isinstance(first, (tuple, list)) else 1
    if n_parts == 1:
        x = np.stack([p.feature if not isinstance(p.feature, (tuple, list))
                      else p.feature[0] for p in pairs])
    else:
        x = [np.stack([p.feature[i] for p in pairs]) for i in range(n_parts)]
    labels = [p.label for p in pairs]
    y = None if any(l is None for l in labels) \
        else np.asarray(labels, dtype=np.int32)
    return x, y


def row_to_sample(row, column_info: ColumnFeatureInfo,
                  model_type: str = "wide_n_deep"):
    """Reference ``row_to_sample`` (utils.py:88): the BigDL Sample is a
    feature+LABEL record, so this returns ``(feature, label)`` — unlike
    ``row_to_feature``, which assembles features only."""
    try:
        label = row[column_info.label]
    except (KeyError, IndexError):
        label = None
    return (row_to_feature(row, column_info, model_type),
            None if label is None else int(label))
