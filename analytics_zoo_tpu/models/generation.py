"""Autoregressive decoding with a static-shape KV cache (VERDICT r4 #3).

Every reference zoo family ships usable inference
(``ObjectDetector.predictImageSet``, ``Recommender.recommendForUser`` —
zoo/.../models/image/objectdetection/ObjectDetector.scala,
recommendation/Recommender.scala:36-86); the LM flagship's analogue is
``TransformerLM.generate``: prefill the prompt in ONE batched causal
forward (MXU-sized matmuls, the pallas path), then decode token-by-token
against per-layer K/V caches under one ``jit`` — a ``lax.scan`` over
steps with static shapes (cache length = prompt + max_new), so the whole
generation is a single compiled computation with no per-token dispatch.

The decode math mirrors ``TransformerLM.build_model`` exactly (pre-norm
blocks, gelu MLP or Switch-MoE sublayer, final LN + lm_head); the
prefix-consistency tests in ``tests/test_generate.py`` pin the two paths
together position-by-position.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention_bhsd
from ..parallel.expert import MoEParams, expert_capacity, switch_moe
from ..pipeline.api.keras.activations import get as get_activation

_gelu = get_activation("gelu")


def _block_params(params, i, moe):
    """Collect layer-i block params from the TransformerLM param tree."""
    bp = {"ln_a": params[f"ln_attn_{i}"], "attn": params[f"attn_{i}"],
          "ln_m": params[f"ln_mlp_{i}"]}
    if moe:
        bp["moe"] = params[f"moe_{i}"]
    else:
        bp["up"] = params[f"mlp_up_{i}"]
        bp["down"] = params[f"mlp_down_{i}"]
    return bp


def _layer_norm(p, x, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * p["gamma"] + p["beta"]


def _mlp(bp, f):
    if "moe" in bp:
        d = f.shape[-1]
        flat = f.reshape(-1, d)
        p = MoEParams(**{k: bp["moe"][k] for k in MoEParams._fields})
        # decode runs DROP-FREE (capacity = token count): with a handful
        # of tokens per step, train-time capacity limits would silently
        # zero sublayer outputs and degrade generation for nothing — the
        # Switch recipe raises capacity at inference
        out, _ = switch_moe(flat, p, capacity=flat.shape[0])
        return out.reshape(f.shape)
    return _gelu(f @ bp["up"]["W"] + bp["up"]["b"]) @ bp["down"]["W"] \
        + bp["down"]["b"]


def _prefill(params, hyper, prompt, cache_len):
    """Batched prompt pass: causal attention over the whole prompt in one
    forward (the training-shaped compute), writing each layer's K/V into
    position [0, s_p) of a (b, heads, cache_len, d) cache and returning
    the last position's hidden state."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    s_p = prompt.shape[1]
    x = jnp.take(params["tok_embed"]["embeddings"],
                 prompt.astype(jnp.int32), axis=0)
    x = x + params["pos_embed"]["table"][:s_p].astype(
        x.dtype)
    caches = []
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wq"])
        k = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wk"])
        v = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wv"])
        o = attention_bhsd(q, k, v, causal=True)
        x = x + jnp.einsum("bhsd,hde->bse", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        pad = [(0, 0), (0, 0), (0, cache_len - s_p), (0, 0)]
        caches.append((jnp.pad(k, pad), jnp.pad(v, pad)))
    return x[:, -1, :], caches


def _decode_step(params, hyper, caches, x_tok, pos):
    """One cached decode step: ``x_tok`` is the (b, d_model) embedding of
    the current token (token + positional), ``pos`` its position.
    Returns (logits, updated caches)."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    n_heads = hyper["n_heads"]
    x = x_tok
    new_caches = []
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        ck, cv = caches[i]
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wq"])
        k = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wk"])
        v = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wv"])
        ck = lax.dynamic_update_slice_in_dim(ck, k[:, :, None, :], pos,
                                             axis=2)
        cv = lax.dynamic_update_slice_in_dim(cv, v[:, :, None, :], pos,
                                             axis=2)
        d = q.shape[-1]
        scores = jnp.einsum("bhd,bhtd->bht", q, ck) / math.sqrt(d)
        t = ck.shape[2]
        valid = jnp.arange(t)[None, None, :] <= pos
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        o = jnp.einsum("bht,bhtd->bhd", probs.astype(cv.dtype), cv)
        x = x + jnp.einsum("bhd,hde->be", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        new_caches.append((ck, cv))
    x = _layer_norm(params["ln_final"], x)
    logits = x @ params["lm_head"]["W"] + params["lm_head"]["b"]
    return logits, new_caches


def _sample(logits, rng, temperature: float, top_k: Optional[int]):
    """Greedy when temperature == 0, else temperature softmax with
    optional top-k truncation.  Static branch: temperature/top_k are
    Python values baked into the compiled plan."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled >= kth, scaled, -1e30)
    return jax.random.categorical(rng, scaled, axis=-1)


def build_generate_fn(hyper, s_p: int, max_new: int, temperature: float,
                      top_k: Optional[int]):
    """Compile one generation plan: (params, prompt, rng) -> (b, max_new)
    sampled token ids.  Static: prompt length, step count, sampling
    config.  The scan carries the caches, so the whole decode is one
    XLA while-loop — no per-token host dispatch."""
    cache_len = s_p + max_new
    pos_table_key = "pos_embed"
    emb_key = "tok_embed"

    @jax.jit
    def run(params, prompt, rng):
        last_hidden, caches = _prefill(params, hyper, prompt, cache_len)
        x = _layer_norm(params["ln_final"], last_hidden)
        logits0 = x @ params["lm_head"]["W"] + params["lm_head"]["b"]
        rng0, rng_loop = jax.random.split(rng)
        tok0 = _sample(logits0, rng0, temperature, top_k)

        def step(carry, i):
            tok, caches, r = carry
            r, r_step = jax.random.split(r)
            pos = s_p + i
            emb = jnp.take(params[emb_key]["embeddings"],
                           tok.astype(jnp.int32), axis=0)
            emb = emb + lax.dynamic_index_in_dim(
                params[pos_table_key]["table"], pos, keepdims=False
            ).astype(emb.dtype)
            logits, caches = _decode_step(params, hyper, caches, emb, pos)
            nxt = _sample(logits, r_step, temperature, top_k)
            return (nxt, caches, r), tok

        (_, _, _), toks = lax.scan(
            step, (tok0, caches, rng_loop), jnp.arange(max_new))
        return jnp.swapaxes(toks, 0, 1)  # (steps, b) -> (b, steps)

    return run


def generate(model, prompt_ids, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             seed: int = 0) -> np.ndarray:
    """Generate continuations for a batch of equal-length prompts.

    Args:
        model: a (trained or loaded) :class:`TransformerLM`.
        prompt_ids: (batch, prompt_len) int token ids; prompt_len +
            max_new_tokens must fit ``max_len``.
        max_new_tokens: number of tokens to decode.
        temperature: 0.0 = greedy argmax; > 0 samples from the
            temperature-scaled distribution.
        top_k: optional truncation to the k most likely tokens before
            sampling (ignored when greedy).
    Returns:
        (batch, prompt_len + max_new_tokens) int32 ids — prompt
        followed by the generated continuation.
    """
    prompt = np.asarray(prompt_ids)
    if prompt.ndim != 2:
        raise ValueError(f"prompt_ids must be (batch, prompt_len), got "
                         f"shape {prompt.shape}")
    h = model.hyper
    s_p = int(prompt.shape[1])
    total = s_p + int(max_new_tokens)
    if total > h["max_len"]:
        raise ValueError(
            f"prompt ({s_p}) + max_new_tokens ({max_new_tokens}) = "
            f"{total} exceeds max_len ({h['max_len']})")
    # the decode path is implementation-agnostic: it reads params by
    # layer name and computes its own cached attention, so a model
    # TRAINED with ring (sequence-parallel) attention decodes here
    # unchanged — the KV cache for one sequence fits one device, which
    # is why there is no ring decode.  (Params under any strategy are
    # replicated or resharded by the jit on first call.)
    trainer = model.ensure_inference_ready()
    key = (s_p, int(max_new_tokens), float(temperature),
           None if top_k is None else int(top_k))
    # LRU-bounded compiled-plan cache: every distinct (prompt_len,
    # max_new, sampling) tuple is its own XLA executable — chat-style
    # callers should pad prompts to a few bucket lengths, and the bound
    # keeps a long-lived server from accumulating executables forever
    cache = getattr(model, "_generate_fns", None)
    if cache is None:
        import collections
        cache = model._generate_fns = collections.OrderedDict()
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = build_generate_fn(
            h, s_p, int(max_new_tokens), float(temperature),
            None if top_k is None else int(top_k))
        while len(cache) > 8:
            cache.popitem(last=False)
    else:
        cache.move_to_end(key)
    toks = fn(trainer.state.params, jnp.asarray(prompt),
              jax.random.PRNGKey(seed))
    return np.concatenate([prompt.astype(np.int32),
                           np.asarray(jax.device_get(toks),
                                      np.int32)], axis=1)
